"""Serving-resilience tests: the engine_step= fault grammar, admission
control + load shedding (token bucket, bounded queue, priority
displacement, SLO-aware shed pass), the degradation ladder, queued-
deadline expiry, the draining /healthz, and the Supervisor's
rebuild-and-replay guarantees — every submitted request reaches a
terminal state, greedy outputs are bit-identical to a fault-free run,
restarts are bounded by the circuit breaker, and compile counters stay
pinned at one per engine build."""
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dla_tpu.resilience.faults import FaultPlan
from dla_tpu.serving import (
    TERMINAL_STATES,
    AdmissionController,
    DegradationLadder,
    PageAllocator,
    PagedKVCache,
    PageGeometry,
    Request,
    RequestState,
    Scheduler,
    SchedulerConfig,
    ServingConfig,
    ServingEngine,
    ShedConfig,
    Supervisor,
    SupervisorConfig,
)


# ---------------------------------------------------------------------------
# fault-plan grammar: the engine_step= site
# ---------------------------------------------------------------------------

def test_fault_plan_engine_step_grammar_and_sites():
    plan = FaultPlan.parse(
        "step=3:nan;engine_step=2:wedge:0.5;engine_step=5:burst=4;"
        "engine_step=7:device_error;engine_step=9:nan_logits")
    # sites are disjoint: a training-step query never consumes a
    # serving entry and vice versa
    assert plan.take("nan", 3, site="engine_step") is None
    assert plan.take("wedge", 2) is None          # default site="step"
    f = plan.take("wedge", 2, site="engine_step")
    assert f is not None and f.arg == 0.5
    f = plan.take("burst", 5, site="engine_step")
    assert f is not None and int(f.arg) == 4
    assert plan.take("nan", 3) is not None
    # spec() round-trips both sites
    spec = FaultPlan.parse(
        "engine_step=5:burst=4;step=1:io_error").spec()
    rt = FaultPlan.parse(spec)
    assert rt.take("burst", 5, site="engine_step") is not None
    assert rt.take("io_error", 1) is not None


def test_fault_plan_rejects_unknown_serving_kind():
    with pytest.raises(ValueError, match="engine_step"):
        FaultPlan.parse("engine_step=3:nan")      # training-only kind
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("step=3:wedge")           # serving-only kind


def test_shed_config_from_config():
    assert ShedConfig.from_config(None) is None
    assert ShedConfig.from_config({"enabled": False}) is None
    cfg = ShedConfig.from_config({"max_queue_depth": 4, "rate": 2.0})
    assert cfg.max_queue_depth == 4 and cfg.rate == 2.0
    with pytest.raises(ValueError, match="unknown shed config"):
        ShedConfig.from_config({"max_depth": 4})
    with pytest.raises(ValueError, match="unknown supervisor config"):
        SupervisorConfig.from_config({"timeout": 1})


# ---------------------------------------------------------------------------
# admission / shedding decision logic (host-only scheduler stand-in)
# ---------------------------------------------------------------------------

class _Cfg:
    num_layers = 1
    num_kv_heads = 1
    head_dim_ = 2


class _ModelStub:
    cfg = _Cfg()
    adtype = jnp.float32


def _sched(page_size=4, num_pages=16, num_slots=2, pages_per_slot=4):
    geom = PageGeometry(page_size=page_size, num_pages=num_pages,
                        num_slots=num_slots, pages_per_slot=pages_per_slot)
    cache = PagedKVCache(_ModelStub(), geom)
    widths = [page_size, 2 * page_size, geom.slot_window]
    return Scheduler(cache, SchedulerConfig(), widths)


def _queued(sched, priority=0, arrival=0.0):
    req = Request(prompt_tokens=[1, 2, 3], max_new_tokens=4,
                  arrival_time=arrival, priority=priority)
    sched.submit(req)
    return req


def test_admission_displaces_lowest_priority_on_full_queue():
    sched = _sched()
    gate = AdmissionController(ShedConfig(max_queue_depth=2))
    r1 = _queued(sched, priority=0, arrival=0.0)
    r2 = _queued(sched, priority=0, arrival=1.0)
    # a higher-priority arrival displaces the WORST queued request:
    # lowest priority, newest arrival among equals
    hi = _queued(sched, priority=1, arrival=2.0)
    admitted, victims = gate.on_submit(sched, hi, 2.0)
    assert admitted and victims == [r2]
    sched.cancel(r2, "shed", RequestState.SHED)
    # an equal-priority arrival into a full queue sheds ITSELF
    lo = _queued(sched, priority=0, arrival=3.0)
    admitted, victims = gate.on_submit(sched, lo, 3.0)
    assert not admitted and victims == [lo]
    assert r1.state is RequestState.WAITING     # older peer untouched


def test_shed_pass_enforces_bound_and_slo_burn():
    sched = _sched(num_slots=2)
    gate = AdmissionController(
        ShedConfig(max_queue_depth=4, slo_burn_threshold=1.0))
    reqs = [_queued(sched, arrival=float(i)) for i in range(6)]
    # queue bound only: 6 queued, bound 4 -> 2 victims, newest first
    victims = gate.shed_pass(sched, burn=0.0, level=0)
    assert victims == [reqs[5], reqs[4]]
    # burn at threshold: trim down to num_slots (keep 2 of 6)
    victims = gate.shed_pass(sched, burn=1.0, level=0)
    assert len(victims) == 4
    assert reqs[0] not in victims and reqs[1] not in victims
    # evicted in-flight work (holds generated tokens) is never sheddable
    reqs[0].generated = [9]
    assert reqs[0] not in gate.shed_pass(sched, burn=1.0, level=4)


def test_degradation_ladder_hysteresis_and_events():
    from dla_tpu.telemetry.flight_recorder import FlightRecorder
    rec = FlightRecorder(capacity=32)
    lad = DegradationLadder(ShedConfig(degrade_high=0.8, degrade_low=0.3,
                                       degrade_patience=2), recorder=rec)
    # escalation needs `patience` CONSECUTIVE high-pressure steps
    assert [lad.update(0.9), lad.update(0.2), lad.update(0.9)] == [0, 0, 0]
    assert lad.update(0.9) == 1
    assert lad.update(0.5) == 1                 # mid band holds steady
    assert [lad.update(0.9) for _ in range(8)] == [1, 2, 2, 3, 3, 4, 4, 4]
    assert lad.no_coschedule and lad.shrink_batch
    assert [lad.update(0.1) for _ in range(4)] == [4, 3, 3, 2]
    kinds = [e["kind"] for e in rec.events]
    assert kinds.count("degradation") == 6      # one event per rung move


def test_allocator_reclaim_cached_flushes_to_free_pool():
    a = PageAllocator(8)
    evicted = []
    a.retain_hook = lambda p: True              # park released pages
    a.evict_hook = evicted.append
    held = a.alloc(3)
    a.free(held[:2])
    assert a.free_count == 4 and a.cached_count == 2
    assert a.reclaim_cached() == 2              # ladder rung 1
    assert a.cached_count == 0 and a.free_count == 6
    assert sorted(evicted) == sorted(held[:2])  # index unhooked too
    assert a.cache_evictions == 2
    assert a.reclaim_cached() == 0              # idempotent when empty
    assert a.refcount(held[2]) == 1             # live pages untouched


# ---------------------------------------------------------------------------
# engine-level: gate, queue timeouts, draining healthz, ladder under load
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def serve_setup():
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    # greedy, run-to-length: the replay bit-identity assertions need
    # deterministic sampling and a fixed token budget
    gen = GenerationConfig(max_new_tokens=10, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    return model, params, gen


def _engine(serve_setup, clock=None, **cfg_kw):
    model, params, gen = serve_setup
    kw = dict(page_size=4, num_pages=32, num_slots=2, max_model_len=32,
              max_prefill_batch=2)
    kw.update(cfg_kw)
    extra = {"now": clock} if clock is not None else {}
    return ServingEngine(model, params, gen, ServingConfig(**kw), **extra)


def _prompts(n, seed=5, length=6):
    # uniform length: ONE prefill bucket, so after each engine's first
    # step no compile can land in a watchdog window
    rs = np.random.RandomState(seed)
    return [list(rs.randint(3, 500, (length,))) for _ in range(n)]


def test_engine_token_bucket_sheds_at_gate(serve_setup):
    t = {"now": 0.0}
    eng = _engine(serve_setup, clock=lambda: t["now"],
                  shed={"rate": 1.0, "burst": 1})
    p = _prompts(3)
    r1 = eng.submit(p[0], 4, arrival_time=0.0)
    r2 = eng.submit(p[1], 4, arrival_time=0.0)   # bucket empty: shed
    assert eng.result(r1).state is RequestState.WAITING
    assert eng.result(r2).state is RequestState.SHED
    assert eng.result(r2).finish_reason == "shed"
    assert eng.metrics.requests_shed.value == 1
    t["now"] = 2.0
    r3 = eng.submit(p[2], 4, arrival_time=2.0)   # refilled: admitted
    assert eng.result(r3).state is RequestState.WAITING
    results = eng.run_until_drained(max_steps=500)
    assert results[r1].state is RequestState.FINISHED
    assert results[r3].state is RequestState.FINISHED
    assert any(e["kind"] == "request_shed" for e in eng.recorder.events)
    eng.scheduler.assert_consistent()
    eng.close()


def test_queued_deadline_expiry_counts_queue_timeouts(serve_setup):
    t = {"now": 0.0}
    eng = _engine(serve_setup, clock=lambda: t["now"], num_slots=1)
    p = _prompts(3)
    r_run = eng.submit(p[0], 5, deadline_s=1.0)
    r_queued = eng.submit(p[1], 5, deadline_s=0.5)  # one slot: waits
    eng.submit(p[2], 5)
    eng.step()
    t["now"] = 2.0
    eng.step()
    # both timed out, but only the never-admitted one is a QUEUE
    # timeout — the admission-pressure signal, distinct from slow decode
    assert eng.result(r_run).state is RequestState.TIMEOUT
    assert eng.result(r_queued).state is RequestState.TIMEOUT
    assert eng.metrics.requests_timed_out.value == 2
    assert eng.metrics.queue_timeouts.value == 1
    eng.run_until_drained(max_steps=500)
    eng.close()


def test_healthz_serves_draining_503(serve_setup):
    eng = _engine(serve_setup, metrics_port=0)
    port = eng.metrics_server.port
    url = f"http://127.0.0.1:{port}/healthz"
    with urllib.request.urlopen(url, timeout=5) as resp:
        assert resp.status == 200
    eng.begin_drain()
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(url, timeout=5)
    assert exc_info.value.code == 503
    assert exc_info.value.read().decode().strip() == "draining"
    eng.close()


def test_degradation_ladder_engages_under_queue_pressure(serve_setup):
    eng = _engine(serve_setup,
                  shed={"max_queue_depth": 4, "degrade_high": 0.5,
                        "degrade_low": 0.1, "degrade_patience": 1})
    for p in _prompts(12, seed=11):
        eng.submit(p, 4, arrival_time=0.0)
    results = eng.run_until_drained(max_steps=500)
    m = eng.metrics
    assert m.requests_shed.value > 0            # bound enforced
    assert m.degradation_level.peak >= 1        # ladder engaged
    assert all(r.state in TERMINAL_STATES for r in results.values())
    assert any(e["kind"] == "degradation" for e in eng.recorder.events)
    eng.scheduler.assert_consistent()
    eng.close()


# ---------------------------------------------------------------------------
# the Supervisor: chaos, replay determinism, breaker
# ---------------------------------------------------------------------------

def _supervised(serve_setup, plan, engines, max_restarts=3, **cfg_kw):
    def factory():
        eng = _engine(serve_setup, fault_plan=plan, **cfg_kw)
        engines.append(eng)
        return eng
    return Supervisor(factory, SupervisorConfig(
        watchdog_timeout_s=0.05, watchdog_poll_s=0.01,
        max_restarts=max_restarts))


def test_supervisor_chaos_replay_is_bit_identical(serve_setup):
    """The acceptance gate: wedge + device error + NaN logits across one
    supervised run. Every request terminal, COMPLETED greedy outputs
    bit-identical to a fault-free run, exactly one restart per injected
    fault (breaker untripped), decode compiles pinned at 1 per build."""
    prompts = _prompts(6, seed=0)

    eng = _engine(serve_setup)
    base_rids = [eng.submit(p, 10) for p in prompts]
    base = eng.run_until_drained(max_steps=500)
    baseline = [list(base[r].generated) for r in base_rids]
    eng.close()

    engines = []
    plan = ("engine_step=2:wedge:0.3;engine_step=4:device_error;"
            "engine_step=6:nan_logits")
    sup = _supervised(serve_setup, plan, engines)
    rids = [sup.submit(p, 10) for p in prompts]
    results = sup.run(max_steps=500)
    sup.close()

    assert sup.failures == ["wedge", "device_error", "nan_logits"]
    assert sup.restarts == 3 and not sup.tripped
    for i, rid in enumerate(rids):
        req = results[rid]
        assert req.state is RequestState.FINISHED
        assert list(req.generated) == baseline[i]   # bit-identical
    # static-shape invariant holds per engine build
    assert [e.decode_compiles for e in engines] == [1] * len(engines)
    assert all(e.prefill_chunk_compiles == 0 for e in engines)
    final = engines[-1]
    assert final.metrics.supervisor_restarts.value == 3
    assert final.metrics.replayed_requests.value == sup.replayed
    assert final.metrics.breaker_open.value == 0.0


def test_supervisor_chaos_with_chunked_prefill_cache(serve_setup):
    """Same chaos through the chunked-prefill + prefix-cache engine:
    replay stays bit-identical and the chunk compile pins at 1/build."""
    prompts = _prompts(4, seed=3, length=8)
    eng = _engine(serve_setup, prefill_chunk=4, prefix_cache=True)
    base_rids = [eng.submit(p, 8) for p in prompts]
    base = eng.run_until_drained(max_steps=500)
    baseline = [list(base[r].generated) for r in base_rids]
    eng.close()

    engines = []
    plan = "engine_step=3:device_error;engine_step=5:nan_logits"
    sup = _supervised(serve_setup, plan, engines,
                      prefill_chunk=4, prefix_cache=True)
    rids = [sup.submit(p, 8) for p in prompts]
    results = sup.run(max_steps=500)
    sup.close()
    assert sup.restarts == 2 and not sup.tripped
    for i, rid in enumerate(rids):
        assert results[rid].state is RequestState.FINISHED
        assert list(results[rid].generated) == baseline[i]
    assert [e.prefill_chunk_compiles for e in engines] == \
        [1] * len(engines)


def test_supervisor_chaos_with_speculative_decode(serve_setup):
    """Same chaos through the speculative (draft/verify) engine: a wedge
    and a device error land mid-round, replay stays bit-identical to a
    fault-free speculative run, draft/verify compiles pin at 1 per
    build, and the page-pool partition invariant holds after the
    restarts — rolled-back draft tails never leak pages."""
    prompts = _prompts(4, seed=9)
    spec = {"enabled": True, "k": 3, "draft": "self"}
    eng = _engine(serve_setup, speculative=spec)
    base_rids = [eng.submit(p, 12) for p in prompts]
    base = eng.run_until_drained(max_steps=500)
    baseline = [list(base[r].generated) for r in base_rids]
    eng.close()

    engines = []
    # speculative decode finishes in few engine steps (K+1 commits per
    # round), so the faults sit early and the 12-token budget keeps
    # every build mid-round long enough for its fault to land
    plan = "engine_step=1:wedge:0.3;engine_step=2:device_error"
    sup = _supervised(serve_setup, plan, engines, speculative=spec)
    rids = [sup.submit(p, 12) for p in prompts]
    results = sup.run(max_steps=500)
    sup.close()

    assert sup.failures == ["wedge", "device_error"]
    assert sup.restarts == 2 and not sup.tripped
    for i, rid in enumerate(rids):
        req = results[rid]
        assert req.state is RequestState.FINISHED
        assert list(req.generated) == baseline[i]   # bit-identical
    assert [e.spec_draft_compiles for e in engines] == [1] * len(engines)
    assert [e.spec_verify_compiles for e in engines] == [1] * len(engines)
    final = engines[-1]
    final.scheduler.assert_consistent()     # no page leaks after restart
    assert final.cache.allocator.used_count == 0
    assert final.metrics.supervisor_restarts.value == 2


def test_supervisor_burst_fault_invokes_hook(serve_setup):
    engines = []
    bursts = []
    sup = _supervised(serve_setup, "engine_step=1:burst=3", engines)
    sup.on_burst = bursts.append
    sup.submit(_prompts(1)[0], 4)
    sup.run(max_steps=200)
    sup.close()
    assert bursts == [3]
    assert sup.restarts == 0


def test_supervisor_burst_default_submits_low_priority(serve_setup):
    engines = []
    sup = _supervised(serve_setup, "engine_step=1:burst=2", engines,
                      shed={"max_queue_depth": 64})
    rid = sup.submit(_prompts(1)[0], 4)
    results = sup.run(max_steps=200)
    sup.close()
    assert len(results) == 3                    # 1 real + 2 synthetic
    assert results[rid].state is RequestState.FINISHED
    synth = [r for k, r in results.items() if k != rid]
    assert all(r.priority == -1 for r in synth)
    assert all(r.state in TERMINAL_STATES for r in synth)


def test_supervisor_breaker_trips_and_drains(serve_setup):
    """Restart budget exhausted: the breaker trips, the rebuilt engine
    comes up draining (healthz 503 `draining`, breaker gauge 1), and a
    further failure resolves all in-flight work terminally as SHED —
    the client sees final statuses, never a hang."""
    engines = []
    plan = ("engine_step=1:device_error;engine_step=1:device_error;"
            "engine_step=1:device_error")
    sup = _supervised(serve_setup, plan, engines, max_restarts=1,
                      metrics_port=0)
    rids = [sup.submit(p, 10) for p in _prompts(4, seed=2)]
    results = sup.run(max_steps=500)
    assert sup.tripped
    assert sup.restarts >= 2
    final = engines[-1]
    assert final.draining
    assert final.metrics.breaker_open.value == 1.0
    assert all(results[r].state in TERMINAL_STATES for r in rids)
    assert any(results[r].state is RequestState.SHED for r in rids)
    port = final.metrics_server.port
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5)
    assert exc_info.value.code == 503
    assert exc_info.value.read().decode().strip() == "draining"
    sup.close()


@pytest.mark.slow
def test_supervisor_chaos_soak(serve_setup):
    """Soak: repeated fault waves (every kind, plus bursts) over a
    larger request population. The invariants that must survive
    arbitrary fault interleaving: zero lost requests, zero hangs, and
    scheduler/allocator consistency on every surviving engine."""
    engines = []
    plan = ";".join(
        [f"engine_step={s}:wedge:0.2" for s in (2, 30)]
        + [f"engine_step={s}:device_error" for s in (6, 40)]
        + [f"engine_step={s}:nan_logits" for s in (10,)]
        + [f"engine_step={s}:burst=4" for s in (4, 20)])
    sup = _supervised(serve_setup, plan, engines, max_restarts=10,
                      shed={"max_queue_depth": 16})
    rids = [sup.submit(p, 8, priority=i % 3)
            for i, p in enumerate(_prompts(16, seed=4))]
    results = sup.run(max_steps=2000)
    sup.close()
    assert all(r.state in TERMINAL_STATES for r in results.values())
    assert not sup.tripped
    completed = [r for r in rids
                 if results[r].state is RequestState.FINISHED]
    assert completed                            # real work got through
    assert all(len(results[r].generated) == 8 for r in completed)
    engines[-1].scheduler.assert_consistent()
