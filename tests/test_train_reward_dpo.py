"""End-to-end smoke tests for reward-model and DPO training on the CPU mesh."""
import json

import numpy as np
import yaml

from dla_tpu.data.jsonl import write_jsonl


def _pref_records(n=48, seed=0):
    """Chosen responses are polite/helpful, rejected are curt — a signal a
    tiny model can separate within a few dozen steps."""
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        a, b = int(rng.integers(0, 30)), int(rng.integers(0, 30))
        recs.append({
            "prompt": f"add {a} {b}",
            "chosen": f"the answer is {a + b} thanks",
            "rejected": "no idea",
        })
    return recs


def _base_cfg(tmp_path, name):
    return {
        "experiment_name": name,
        "seed": 0,
        "data": {"source": "local",
                 "train_path": str(tmp_path / "pref.jsonl")},
        "optimization": {
            "total_batch_size": 16, "micro_batch_size": 2,
            "learning_rate": 1e-3, "warmup_steps": 2,
            "max_train_steps": 10, "lr_scheduler": "cosine",
            "max_grad_norm": 1.0,
        },
        "logging": {
            "output_dir": str(tmp_path / "ckpt"),
            "log_dir": str(tmp_path / "logs"),
            "log_every_steps": 2, "save_every_steps": 0,
        },
        "hardware": {
            "gradient_accumulation_steps": 2,
            "mesh": {"data": 2, "fsdp": 2, "model": 2},
        },
    }


def _metric(log_dir, key):
    out = []
    with open(log_dir / "metrics.jsonl") as fh:
        for line in fh:
            rec = json.loads(line)
            if key in rec:
                out.append((rec["step"], rec[key]))
    return out


def test_reward_training_learns_preferences(tmp_path):
    from dla_tpu.training.train_reward import main
    write_jsonl(tmp_path / "pref.jsonl", _pref_records())
    cfg = _base_cfg(tmp_path, "reward_smoke")
    cfg["model"] = {"base_model_name_or_path": "tiny", "tokenizer": "byte",
                    "max_seq_length": 32, "pooling": "last_token",
                    "dropout": 0.1}
    cfg["optimization"]["max_train_steps"] = 20
    cfg["optimization"]["learning_rate"] = 2e-3
    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump(cfg))
    main(["--config", str(p)])
    losses = _metric(tmp_path / "logs", "train/loss_instant")
    accs = _metric(tmp_path / "logs", "train/acc")
    assert np.mean([v for _, v in losses[-2:]]) < losses[0][1]
    assert accs[-1][1] > 0.6  # pairwise accuracy should beat chance


def test_dpo_training_improves_preference_rate(tmp_path):
    from dla_tpu.training.train_dpo import main
    write_jsonl(tmp_path / "pref.jsonl", _pref_records())
    cfg = _base_cfg(tmp_path, "dpo_smoke")
    cfg["model"] = {"policy_model_name_or_path": "tiny", "tokenizer": "byte",
                    "max_seq_length": 24, "beta": 0.5}
    cfg["data"]["preference_path"] = cfg["data"].pop("train_path")
    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump(cfg))
    main(["--config", str(p)])
    losses = _metric(tmp_path / "logs", "train/loss_instant")
    prefs = _metric(tmp_path / "logs", "train/preference_rate")
    # DPO loss starts at log(2) with identical policy/ref and must fall
    assert abs(losses[0][1] - np.log(2)) < 0.35
    assert losses[-1][1] < losses[0][1]
    assert prefs[-1][1] > 0.5


def test_dpo_mesh_shapes_vary(tmp_path):
    """Same run on a pure-fsdp mesh — sharding-shape robustness."""
    from dla_tpu.training.train_dpo import main
    write_jsonl(tmp_path / "pref.jsonl", _pref_records(n=32))
    cfg = _base_cfg(tmp_path, "dpo_mesh")
    cfg["model"] = {"policy_model_name_or_path": "tiny", "tokenizer": "byte",
                    "max_seq_length": 24, "beta": 0.1}
    cfg["data"]["preference_path"] = cfg["data"].pop("train_path")
    cfg["hardware"]["mesh"] = {"data": 1, "fsdp": 8, "model": 1}
    cfg["optimization"]["max_train_steps"] = 4
    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump(cfg))
    main(["--config", str(p)])
    losses = _metric(tmp_path / "logs", "train/loss_instant")
    assert losses and np.isfinite(losses[-1][1])
