"""Two-process jax.distributed CPU test (round-2 verdict next-step 7):
exercises the code paths that silently no-op at process_count() == 1 —
make_array_from_process_local_data, local_numpy's multi-host branch, the
cross-host barrier, and per-host checkpoint shard writes — then restores
the 2-host checkpoint in THIS single process onto a different topology
(the bug class that only appears at process_count > 1 and eats 70B runs).
"""
import json
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_worker_world(worker: str, n_procs: int, devices_per_proc: int,
                      extra_args, ok_marker: str, timeout: int):
    """Launch ``worker`` as an n-process jax.distributed world and assert
    every rank exits 0 and prints its OK marker. Returns the outputs."""
    sys.path.insert(0, str(REPO_ROOT))
    from _cpuhost import scrubbed_cpu_env

    port = _free_port()
    env = scrubbed_cpu_env(devices_per_proc, str(REPO_ROOT))
    procs = [
        subprocess.Popen(
            [sys.executable, str(REPO_ROOT / "tests" / worker),
             str(port), str(rank), *map(str, extra_args)],
            env=env, cwd=str(REPO_ROOT),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for rank in range(n_procs)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"{worker} world timed out")
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"{worker} rank {rank} failed:\n{out[-4000:]}"
        assert ok_marker.format(rank=rank) in out
    return outs


@pytest.fixture(scope="module")
def two_host_checkpoint(tmp_path_factory):
    """Run the 2-process worker world to completion; yield its ckpt dir."""
    outdir = tmp_path_factory.mktemp("dist_ckpt")
    _run_worker_world("_dist_worker.py", 2, 4, [outdir],
                      "[worker {rank}] OK", timeout=300)
    return outdir


def test_two_process_world_and_shard_writes(two_host_checkpoint):
    """Both workers passed their in-world asserts (global mean over the
    2-host batch, local_numpy slices); the checkpoint they wrote must be
    sharded — one file per index region, no gather through host 0."""
    ckpt = two_host_checkpoint / "step_00000007"
    index = json.loads((ckpt / "index.json").read_text())
    w_meta = index["leaves"]["w"]
    assert "shards" in w_meta, "w should be written as per-region shards"
    # fsdp=2 x model=2 -> 4 distinct index regions
    assert len(w_meta["shards"]) == 4, w_meta["shards"]
    for sh in w_meta["shards"]:
        assert (ckpt / sh["file"]).is_file(), sh
    # replicated leaf: multi-host arrays aren't fully addressable, so it
    # goes through the shard path as ONE whole-array region written by
    # its replica-0 owner (no duplicate writes from the other host)
    b_meta = index["leaves"]["b"]
    assert len(b_meta["shards"]) == 1, b_meta
    assert b_meta["shards"][0]["index"] == [[0, 12]]
    assert (two_host_checkpoint / "latest").read_text().strip() == \
        "step_00000007"


def test_four_process_rlhf_phase_chain(tmp_path):
    """Four-process RLHF smoke (r4 VERDICT item 8): SFT writes its
    checkpoint chain across 4 hosts, then the RLHF loop loads the
    policy through the `latest` pointer and runs rollout steps whose
    prompt sampling and rollout-row assembly are sharded per host
    (train_rlhf.py local_bs = batch / process_count). 2 virtual devices
    per process = one 8-device world."""
    _run_worker_world("_rlhf_dist_worker.py", 4, 2, [tmp_path],
                      "[rlhf-worker {rank}] OK", timeout=600)


def test_cross_topology_restore_from_two_hosts(two_host_checkpoint):
    """Restore the 2-process checkpoint in this single process onto a
    different mesh layout; values must round-trip exactly."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dla_tpu.checkpoint.checkpointer import Checkpointer
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    # different topology than the writers': all 8 devices on fsdp
    mesh = build_mesh(MeshConfig(data=1, fsdp=8, model=1, sequence=1))
    template = {"w": jnp.zeros((16, 12), jnp.float32),
                "b": jnp.zeros((12,), jnp.float32)}
    shardings = {"w": NamedSharding(mesh, P("fsdp", None)),
                 "b": NamedSharding(mesh, P())}
    ck = Checkpointer(str(two_host_checkpoint))
    tree, aux = ck.restore(template, shardings=shardings)
    assert aux["who"] == "dist_worker"
    want = np.arange(16 * 12, dtype=np.float32).reshape(16, 12)
    np.testing.assert_array_equal(np.asarray(tree["w"]), want)
    np.testing.assert_array_equal(np.asarray(tree["b"]),
                                  np.arange(12, dtype=np.float32))
    assert tree["w"].sharding.spec == P("fsdp", None)
