"""Two-process jax.distributed CPU test (round-2 verdict next-step 7):
exercises the code paths that silently no-op at process_count() == 1 —
make_array_from_process_local_data, local_numpy's multi-host branch, the
cross-host barrier, and per-host checkpoint shard writes — then restores
the 2-host checkpoint in THIS single process onto a different topology
(the bug class that only appears at process_count > 1 and eats 70B runs).
"""
import json
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def two_host_checkpoint(tmp_path_factory):
    """Run the 2-process worker world to completion; yield its ckpt dir."""
    sys.path.insert(0, str(REPO_ROOT))
    from _cpuhost import scrubbed_cpu_env

    outdir = tmp_path_factory.mktemp("dist_ckpt")
    port = _free_port()
    env = scrubbed_cpu_env(4, str(REPO_ROOT))  # 4 virtual devices per proc
    procs = [
        subprocess.Popen(
            [sys.executable, str(REPO_ROOT / "tests" / "_dist_worker.py"),
             str(port), str(rank), str(outdir)],
            env=env, cwd=str(REPO_ROOT),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker timed out")
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {rank} failed:\n{out}"
        assert f"[worker {rank}] OK" in out
    return outdir


def test_two_process_world_and_shard_writes(two_host_checkpoint):
    """Both workers passed their in-world asserts (global mean over the
    2-host batch, local_numpy slices); the checkpoint they wrote must be
    sharded — one file per index region, no gather through host 0."""
    ckpt = two_host_checkpoint / "step_00000007"
    index = json.loads((ckpt / "index.json").read_text())
    w_meta = index["leaves"]["w"]
    assert "shards" in w_meta, "w should be written as per-region shards"
    # fsdp=2 x model=2 -> 4 distinct index regions
    assert len(w_meta["shards"]) == 4, w_meta["shards"]
    for sh in w_meta["shards"]:
        assert (ckpt / sh["file"]).is_file(), sh
    # replicated leaf: multi-host arrays aren't fully addressable, so it
    # goes through the shard path as ONE whole-array region written by
    # its replica-0 owner (no duplicate writes from the other host)
    b_meta = index["leaves"]["b"]
    assert len(b_meta["shards"]) == 1, b_meta
    assert b_meta["shards"][0]["index"] == [[0, 12]]
    assert (two_host_checkpoint / "latest").read_text().strip() == \
        "step_00000007"


def test_cross_topology_restore_from_two_hosts(two_host_checkpoint):
    """Restore the 2-process checkpoint in this single process onto a
    different mesh layout; values must round-trip exactly."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dla_tpu.checkpoint.checkpointer import Checkpointer
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    # different topology than the writers': all 8 devices on fsdp
    mesh = build_mesh(MeshConfig(data=1, fsdp=8, model=1, sequence=1))
    template = {"w": jnp.zeros((16, 12), jnp.float32),
                "b": jnp.zeros((12,), jnp.float32)}
    shardings = {"w": NamedSharding(mesh, P("fsdp", None)),
                 "b": NamedSharding(mesh, P())}
    ck = Checkpointer(str(two_host_checkpoint))
    tree, aux = ck.restore(template, shardings=shardings)
    assert aux["who"] == "dist_worker"
    want = np.arange(16 * 12, dtype=np.float32).reshape(16, 12)
    np.testing.assert_array_equal(np.asarray(tree["w"]), want)
    np.testing.assert_array_equal(np.asarray(tree["b"]),
                                  np.arange(12, dtype=np.float32))
    assert tree["w"].sharding.spec == P("fsdp", None)
