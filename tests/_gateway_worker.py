"""Worker process for the federation acceptance test
(test_federation.py): one gateway-fronted serving fleet on this host.

Builds the deterministic tiny Transformer (params from
``jax.random.key(7)`` — every worker and the in-process reference hold
bit-identical weights), fronts a 2-member ``FleetRouter`` with a
``ServingGateway`` on an ephemeral port, heartbeats into the shared
gossip directory, prints ``READY <name> <port>`` and serves until
killed. An optional per-step delay keeps streams open long enough for
the parent to kill this worker MID-STREAM (the zero-loss replay path)
or migrate a live request away.

Usage: python tests/_gateway_worker.py <gossip_dir> <name> [slow_ms]
[spool_dir] (launched with a scrubbed CPU env; see
_cpuhost.scrubbed_cpu_env). A non-empty ``spool_dir`` installs an
enabled process tracer spooling into it — the distributed-tracing
acceptance test merges every worker's spool with tools/trace_merge.py.
"""
import sys
import time


def main() -> None:
    gossip_dir, name = sys.argv[1], sys.argv[2]
    slow_ms = float(sys.argv[3]) if len(sys.argv) > 3 else 0.0
    spool_dir = sys.argv[4] if len(sys.argv) > 4 else ""

    import jax

    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.serving import (
        FleetConfig,
        FleetRouter,
        GossipBeater,
        ServingConfig,
        ServingEngine,
        ServingGateway,
    )

    if spool_dir:
        from dla_tpu.telemetry.trace import Tracer, install_tracer
        install_tracer(Tracer.from_config(
            {"enabled": True, "spool_dir": spool_dir, "proc": name}))

    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    gen = GenerationConfig(max_new_tokens=16, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    kw = dict(page_size=4, num_pages=64, num_slots=2, max_model_len=32,
              max_prefill_batch=2, prefill_chunk=4, prefix_cache=True,
              fault_plan="")

    def factory(slot):
        return ServingEngine(model, params, gen, ServingConfig(**kw))

    router = FleetRouter(factory, FleetConfig(engines=2))
    if slow_ms > 0:
        orig_step = router.step

        def slow_step():
            time.sleep(slow_ms / 1000.0)
            return orig_step()
        router.step = router.poll = slow_step

    gw = ServingGateway(router)
    beater = GossipBeater(gw, gossip_dir, name)
    print(f"READY {name} {gw.port}", flush=True)
    try:
        while True:           # serve until the parent kills us
            time.sleep(0.5)
    finally:
        beater.stop()
        gw.close()


if __name__ == "__main__":
    main()
