"""Speculative serving tests: blockwise draft/verify speculation on the
paged KV pool must be an invisible optimisation. Greedy AND
per-request-seeded sampled outputs are bit-identical to the
non-speculative engine (emitted tokens are the target's own samples —
acceptance only decides how many commit per round), the draft and
verify compiles pin at one per engine build, eviction-recompute is
unchanged, and the spec counters stay monotone across supervisor
restarts."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.generation.engine import GenerationConfig, build_generate_fn
from dla_tpu.generation.speculative import build_speculative_generate_fn
from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import Transformer
from dla_tpu.serving import (
    RequestState,
    SamplingParams,
    ServingConfig,
    ServingEngine,
    Supervisor,
    SupervisorConfig,
)

MAX_NEW = 8
SPEC = {"enabled": True, "k": 3, "draft": "self"}


@pytest.fixture(scope="module")
def model_and_params():
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    return model, model.init(jax.random.key(7))


def _prompts(n=4, seed=3):
    rs = np.random.RandomState(seed)
    return [list(rs.randint(3, 500, (length,)))
            for length in rs.randint(4, 10, (n,))]


def _run(model, params, gen, prompts, sampling=None, **cfg_kw):
    """Run prompts to completion on a fresh engine; returns the engine
    (for counter assertions) and the per-prompt Request results."""
    kw = dict(page_size=4, num_pages=32, num_slots=2, max_model_len=32,
              max_prefill_batch=2)
    kw.update(cfg_kw)
    eng = ServingEngine(model, params, gen, ServingConfig(**kw))
    sampling = sampling or [None] * len(prompts)
    rids = [eng.submit(p, MAX_NEW, sampling=sp)
            for p, sp in zip(prompts, sampling)]
    results = eng.run_until_drained(max_steps=500)
    eng.scheduler.assert_consistent()
    return eng, [results[r] for r in rids]


@pytest.mark.parametrize("draft", ["self", "int8"])
def test_spec_greedy_bit_identical_and_compiles_pinned(
        model_and_params, draft):
    """THE parity pin: the speculative engine's greedy stream is
    byte-for-byte the non-speculative engine's (tokens AND logprobs),
    for both the int8 self-draft and the full-precision sanity draft;
    draft/verify each compile exactly once."""
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    prompts = _prompts()
    _, base = _run(model, params, gen, prompts)
    eng, spec = _run(model, params, gen, prompts,
                     speculative={"enabled": True, "k": 3, "draft": draft})
    for i, (b, s) in enumerate(zip(base, spec)):
        assert s.state is RequestState.FINISHED
        assert s.generated == b.generated, f"prompt {i} diverged"
        np.testing.assert_allclose(s.generated_logprobs,
                                   b.generated_logprobs, atol=1e-5, rtol=0)
    assert eng.spec_draft_compiles == 1
    assert eng.spec_verify_compiles == 1
    snap = eng.metrics.snapshot()
    assert snap["serving/spec/rounds"] > 0
    assert snap["serving/spec/proposed_tokens"] > 0
    assert 0.0 < snap["serving/spec/acceptance_rate"] <= 1.0
    if draft == "self":
        # self-draft proposes the target's own choices: full acceptance
        assert snap["serving/spec/acceptance_rate"] == 1.0
        assert snap["serving/spec/rollbacks"] == 0
    assert eng.cache.allocator.used_count == 0


def test_spec_sampled_matches_nonspec_per_request_seeds(model_and_params):
    """Sampled streams are a pure function of (seed, token index): the
    speculative engine reproduces the non-speculative engine bit-for-bit
    under per-request seeded sampling, for both draft kinds — rejected
    draft tails must never perturb the committed stream."""
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=True,
                           temperature=0.9, top_p=0.9, top_k=8,
                           eos_token_id=2, pad_token_id=0)
    prompts = _prompts(seed=5)
    sampling = [SamplingParams(temperature=0.9, top_p=0.9, top_k=8,
                               seed=70 + i, do_sample=True)
                for i in range(len(prompts))]
    _, base = _run(model, params, gen, prompts, sampling=sampling)
    for draft in ("self", "int8"):
        _, spec = _run(
            model, params, gen, prompts, sampling=sampling,
            speculative={"enabled": True, "k": 3, "draft": draft})
        for i, (b, s) in enumerate(zip(base, spec)):
            assert s.generated == b.generated, (draft, i)
            np.testing.assert_allclose(
                s.generated_logprobs, b.generated_logprobs,
                atol=1e-5, rtol=0)


def test_spec_matches_fixed_shape_speculative_engine(model_and_params):
    """Cross-engine pin: the paged speculative engine and the
    fixed-shape speculative generator (same target, self-draft, greedy)
    land on identical tokens — both must equal plain greedy decode."""
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    prompts = _prompts(seed=7)
    width = max(len(p) for p in prompts)
    ids = np.zeros((len(prompts), width), np.int32)
    mask = np.zeros_like(ids)
    for i, p in enumerate(prompts):
        ids[i, :len(p)] = p
        mask[i, :len(p)] = 1
    fn = jax.jit(build_speculative_generate_fn(model, model, gen, gamma=4))
    out = fn(params, params, jnp.asarray(ids), jnp.asarray(mask),
             jax.random.key(0))
    resp = np.asarray(out["response_tokens"])
    rmask = np.asarray(out["response_mask"])
    ref = [[int(t) for t, m in zip(resp[i], rmask[i]) if m]
           for i in range(len(prompts))]
    _, spec = _run(model, params, gen, prompts, speculative=SPEC)
    for i, (r, s) in enumerate(zip(ref, spec)):
        assert s.generated == r, f"prompt {i} diverged"


def test_spec_eviction_recomputes_identically(model_and_params):
    """A pool sized to force mid-decode preemption under speculation:
    the evicted request re-prefills and still lands on the greedy
    reference — rollback bookkeeping must not corrupt recompute."""
    model, params = model_and_params
    rs = np.random.RandomState(11)
    use = [list(rs.randint(3, 500, (4,))) for _ in range(2)]
    gen = GenerationConfig(max_new_tokens=5, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    fn = jax.jit(build_generate_fn(model, gen))
    ids = np.asarray(use, np.int32)
    out = fn(params, jnp.asarray(ids), jnp.ones_like(jnp.asarray(ids)),
             jax.random.key(0))
    resp = np.asarray(out["response_tokens"])
    rmask = np.asarray(out["response_mask"])
    want = [[int(t) for t, m in zip(resp[i], rmask[i]) if m]
            for i in range(len(use))]
    eng = ServingEngine(model, params, gen,
                        ServingConfig(page_size=2, num_pages=8,
                                      num_slots=2, max_model_len=12,
                                      max_prefill_batch=2,
                                      speculative=SPEC))
    rids = [eng.submit(p, 5) for p in use]
    results = eng.run_until_drained(max_steps=500)
    assert eng.metrics.preemptions.value >= 1, (
        "config was meant to force at least one preemption")
    for rid, expect in zip(rids, want):
        req = results[rid]
        assert req.generated == expect, (
            f"eviction recompute diverged (evictions={req.evictions})")
    assert eng.cache.allocator.used_count == 0
    eng.scheduler.assert_consistent()


def test_spec_counters_monotone_across_supervisor_restart(
        model_and_params):
    """Satellite pin: serving/spec/* counters never reset across a
    supervisor rebuild — the final engine's panel equals the SUM of
    every build's own round accounting, and the acceptance-rate gauge
    re-seeds from the carried totals."""
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    prompts = _prompts(seed=9)
    engines = []

    def factory():
        eng = ServingEngine(model, params, gen, ServingConfig(
            page_size=4, num_pages=32, num_slots=2, max_model_len=32,
            max_prefill_batch=2, speculative=SPEC,
            fault_plan="engine_step=3:device_error"))
        engines.append(eng)
        return eng

    sup = Supervisor(factory, SupervisorConfig(
        watchdog_timeout_s=0.05, watchdog_poll_s=0.01, max_restarts=2))
    rids = [sup.submit(p, MAX_NEW) for p in prompts]
    results = sup.run(max_steps=500)
    sup.close()
    assert sup.restarts == 1 and len(engines) == 2
    for rid in rids:
        assert results[rid].state is RequestState.FINISHED
    # the pre-restart engine did at least one spec round before dying
    assert engines[0]._spec_stats["rounds"] > 0
    final = engines[-1]
    for field, ctr in (("rounds", final.metrics.spec_rounds),
                      ("proposed", final.metrics.spec_proposed),
                      ("accepted", final.metrics.spec_accepted),
                      ("rollbacks", final.metrics.spec_rollbacks)):
        total = sum(e._spec_stats[field] for e in engines)
        assert ctr.value == total, (field, ctr.value, total)
        assert ctr.value >= engines[0]._spec_stats[field]  # monotone
    snap = final.metrics.snapshot()
    assert snap["serving/spec/acceptance_rate"] == 1.0  # self-draft
    assert [e.spec_draft_compiles for e in engines] == [1, 1]
    assert [e.spec_verify_compiles for e in engines] == [1, 1]


def test_spec_config_validation(model_and_params):
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=4, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    base = dict(page_size=4, num_pages=32, num_slots=2, max_model_len=32)
    for bad in ({"enabled": True, "k": 0},
                {"enabled": True, "draft": "bogus"},
                {"enabled": True, "gamma": 4}):
        with pytest.raises(ValueError):
            ServingEngine(model, params, gen,
                          ServingConfig(speculative=bad, **base))
    # disabled block is inert: no draft tree, no spec executables
    eng = ServingEngine(model, params, gen, ServingConfig(
        speculative={"enabled": False, "k": 9}, **base))
    assert eng.draft_params is None
    assert eng.spec_draft_compiles == 0
