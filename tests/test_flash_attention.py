"""Flash-attention kernel: numerical parity with the XLA reference
(forward + grads, MHA + GQA), and the model-level backend switch.

Runs the pallas kernel in interpreter mode on CPU; the same code compiles
for TPU."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.ops.attention import causal_attention
from dla_tpu.ops.flash_attention import flash_causal_attention


def _rand_qkv(b, t, h, kh, d, seed=0):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rs.randn(b, t, kh, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, t, kh, d).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("h,kh", [(4, 4), (4, 2)])
def test_flash_matches_xla_forward(h, kh):
    q, k, v = _rand_qkv(2, 16, h, kh, 8)
    got = flash_causal_attention(q, k, v, interpret=True)
    want = causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_multi_block():
    """T larger than one block exercises the online-softmax accumulation."""
    q, k, v = _rand_qkv(1, 32, 2, 2, 8, seed=1)
    got = flash_causal_attention(q, k, v, block_q=8, block_k=8,
                                 interpret=True)
    want = causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_grads_match_xla():
    q, k, v = _rand_qkv(1, 16, 2, 2, 8, seed=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(q, k, v, interpret=True) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_flash_grads_multi_block_gqa():
    """Pallas backward across several q/kv blocks with grouped heads:
    exercises the dQ accumulation, the dK/dV per-q-head kernel, and the
    GQA group-sum."""
    q, k, v = _rand_qkv(2, 48, 4, 2, 8, seed=5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(
            q, k, v, block_q=16, block_k=8, interpret=True) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_model_flash_backend_matches_xla():
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    cfg_x = get_model_config("tiny", attention="xla")
    cfg_f = get_model_config("tiny", attention="flash")
    model_x = Transformer(cfg_x)
    model_f = Transformer(cfg_f)
    params = model_x.init(jax.random.key(0))

    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(1, 100, (2, 16)), jnp.int32)
    mask = jnp.asarray(np.stack([[1] * 16, [1] * 10 + [0] * 6]), jnp.int32)
    out_x = model_x.apply(params, ids, attention_mask=mask)
    out_f = model_f.apply(params, ids, attention_mask=mask)
    # parity on real (unmasked) positions
    np.testing.assert_allclose(
        np.asarray(out_f[0]), np.asarray(out_x[0]), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(out_f[1, :10]), np.asarray(out_x[1, :10]),
        rtol=2e-4, atol=2e-5)


def _packed_segments(b, t, seed=3):
    """Random packed layout: 2 real segments (ids 1, 2) + trailing pads
    (id 0) per row — the data/packing.py convention."""
    rs = np.random.RandomState(seed)
    seg = np.zeros((b, t), np.int32)
    for bi in range(b):
        n1 = rs.randint(2, t - 3)
        n2 = rs.randint(1, t - n1 - 1)
        seg[bi, :n1] = 1
        seg[bi, n1:n1 + n2] = 2
    return jnp.asarray(seg)


def test_flash_segment_ids_match_xla_forward():
    """Packed segment masking inside the kernel == XLA same-segment mask
    (the round-2 verdict's top item: packing + flash must compose)."""
    q, k, v = _rand_qkv(2, 32, 4, 2, 8, seed=7)
    seg = _packed_segments(2, 32)
    got = flash_causal_attention(q, k, v, segment_ids=seg,
                                 block_q=8, block_k=8, interpret=True)
    same = seg[:, :, None] == seg[:, None, :]
    want = causal_attention(q, k, v, kv_segment_mask=same)
    m = np.asarray(seg) > 0  # pad rows (segment 0) are garbage by contract
    for bi in range(2):
        np.testing.assert_allclose(
            np.asarray(got)[bi][m[bi]], np.asarray(want)[bi][m[bi]],
            rtol=2e-4, atol=2e-5)


def test_flash_segment_ids_grads_match_xla():
    q, k, v = _rand_qkv(2, 32, 4, 2, 8, seed=8)
    seg = _packed_segments(2, 32, seed=9)
    same = seg[:, :, None] == seg[:, None, :]
    mask = (seg > 0)[:, :, None, None]

    def loss_flash(q, k, v):
        o = flash_causal_attention(q, k, v, segment_ids=seg,
                                   block_q=8, block_k=8, interpret=True)
        return jnp.sum(jnp.where(mask, o, 0.0) ** 2)

    def loss_xla(q, k, v):
        o = causal_attention(q, k, v, kv_segment_mask=same)
        return jnp.sum(jnp.where(mask, o, 0.0) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_model_flash_backend_packed_matches_unpacked():
    """packing: true + use_flash_attention: true now compose — the packed
    flash forward must equal the per-sequence unpacked forward."""
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    cfg_f = get_model_config("tiny", attention="flash")
    model = Transformer(cfg_f)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(1)
    a, b = rs.randint(1, 100, (6,)), rs.randint(1, 100, (8,))
    packed = jnp.asarray(np.concatenate([a, b, [0, 0]])[None, :], jnp.int32)
    seg = jnp.asarray([[1] * 6 + [2] * 8 + [0] * 2])
    out_packed = model.apply(params, packed, segment_ids=seg)
    out_a = model.apply(params, jnp.asarray(a[None, :], jnp.int32))
    out_b = model.apply(params, jnp.asarray(b[None, :], jnp.int32))
    np.testing.assert_allclose(
        np.asarray(out_packed[0, :6]), np.asarray(out_a[0]),
        rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(out_packed[0, 6:14]), np.asarray(out_b[0]),
        rtol=2e-4, atol=2e-5)


def test_model_flash_packed_grads_match_xla_backend():
    """Full-model gradient parity: flash vs XLA backend on a packed batch
    (exercises the segment-aware backward kernels through the scan)."""
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.losses import cross_entropy_loss

    params = Transformer(get_model_config("tiny")).init(jax.random.key(0))
    rs = np.random.RandomState(2)
    ids = jnp.asarray(rs.randint(1, 100, (2, 16)), jnp.int32)
    seg = _packed_segments(2, 16, seed=11)
    labels = jnp.where(seg > 0, ids, -100)

    def loss(p, backend):
        model = Transformer(get_model_config("tiny", attention=backend))
        logits = model.apply(p, ids, segment_ids=seg)
        return cross_entropy_loss(logits, labels)[0]

    gf = jax.grad(lambda p: loss(p, "flash"))(params)
    gx = jax.grad(lambda p: loss(p, "xla"))(params)
    flat_f, _ = jax.tree_util.tree_flatten(gf)
    flat_x, _ = jax.tree_util.tree_flatten(gx)
    for a, b in zip(flat_f, flat_x):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_flash_shard_map_under_mesh():
    """Under a >1-device mesh the model wraps the kernel in shard_map;
    outputs must keep the batch/heads sharding and match the unsharded
    run (a bare pallas_call would silently replicate under GSPMD)."""
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.parallel.sharding import sharding_tree

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, model=2, sequence=1))
    cfg = get_model_config("tiny-gqa", attention="flash")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(3)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    seg = _packed_segments(4, 16, seed=12)

    want = model.apply(params, ids, segment_ids=seg)
    with jax.sharding.set_mesh(mesh):
        sharded_params = jax.device_put(
            params, sharding_tree(model.partition_specs(), mesh))
        got = jax.jit(lambda p: model.apply(p, ids, segment_ids=seg))(
            sharded_params)
        # the regression this test pins is sharding-only: a bare
        # pallas_call under GSPMD produces identical VALUES but collapses
        # the output to fully-replicated — so assert the layout too
        batch_spec = got.sharding.spec[0]
        assert batch_spec is not None and set(
            batch_spec if isinstance(batch_spec, tuple) else (batch_spec,)
        ) & {"data", "fsdp"}, (
            f"flash output lost its batch sharding: {got.sharding.spec}")
    m = np.asarray(seg) > 0
    for bi in range(4):
        np.testing.assert_allclose(
            np.asarray(got)[bi][m[bi]], np.asarray(want)[bi][m[bi]],
            rtol=2e-3, atol=2e-4)


def test_flash_replicated_fallback_logs_once(capsys):
    """An odd batch (not divisible by the dp shard count) takes the bare
    pallas_call, which GSPMD runs replicated — correct but unpartitioned.
    That silent degradation must announce itself in the logs, once per
    shape (VERDICT r3 weak-item 4)."""
    from dla_tpu.models import transformer as tf_mod
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, model=2, sequence=1))
    cfg = get_model_config("tiny-gqa", attention="flash")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(
        np.random.RandomState(7).randint(1, 100, (3, 16)), jnp.int32)

    tf_mod._REPLICATED_FLASH_LOGGED.clear()
    with jax.sharding.set_mesh(mesh):
        model.apply(params, ids)   # batch 3 % 4 shards != 0
        model.apply(params, ids)   # same shape: no second line
    err = capsys.readouterr().err
    assert err.count("runs REPLICATED") == 1, err


# ------------------------------------------------- sliding window (mistral)


def _naive_windowed(q, k, v, window):
    """Loop reference: q attends kv in (q - window, q]."""
    b, t, h, d = q.shape
    kh = k.shape[2]
    groups = h // kh
    out = np.zeros((b, t, h, d), np.float32)
    qn, kn, vn = map(np.asarray, (q, k, v))
    for bi in range(b):
        for hi in range(h):
            for ti in range(t):
                lo = max(0, ti - window + 1)
                kk = kn[bi, lo:ti + 1, hi // groups]
                vv = vn[bi, lo:ti + 1, hi // groups]
                s = (qn[bi, ti, hi] @ kk.T) * (d ** -0.5)
                w = np.exp(s - s.max())
                w = w / w.sum()
                out[bi, ti, hi] = w @ vv
    return out


def test_xla_window_matches_naive():
    q, k, v = _rand_qkv(2, 16, 2, 2, 8, seed=5)
    got = causal_attention(q, k, v, window=5)
    want = _naive_windowed(q, k, v, 5)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("window", [4, 8, 13])
def test_flash_window_matches_xla(window):
    """Windows smaller than / equal to / not aligned with the block size,
    across multiple blocks (block skip + in-tile mask both exercised)."""
    q, k, v = _rand_qkv(1, 32, 2, 2, 8, seed=6)
    got = flash_causal_attention(q, k, v, block_q=8, block_k=8,
                                 window=window, interpret=True)
    want = causal_attention(q, k, v, window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_window_grads_match_xla():
    q, k, v = _rand_qkv(1, 24, 2, 2, 8, seed=7)

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(
            q, k, v, block_q=8, block_k=8, window=6, interpret=True) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(causal_attention(q, k, v, window=6) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_flash_window_with_segments():
    """Packing and sliding window compose: mask = causal & window & same
    segment."""
    q, k, v = _rand_qkv(2, 16, 2, 2, 8, seed=8)
    seg = _packed_segments(2, 16, seed=9)
    got = flash_causal_attention(q, k, v, segment_ids=seg, window=5,
                                 block_q=8, block_k=8, interpret=True)
    pos = jnp.arange(16)
    seg_mask = (seg[:, :, None] == seg[:, None, :]) & (seg[:, None, :] > 0)
    win_mask = (pos[None, :, None] - pos[None, None, :]) < 5
    want = causal_attention(q, k, v,
                            kv_segment_mask=seg_mask & win_mask)
    m = np.asarray(seg) > 0
    for bi in range(2):
        np.testing.assert_allclose(
            np.asarray(got)[bi][m[bi]], np.asarray(want)[bi][m[bi]],
            rtol=2e-4, atol=2e-5)


def test_model_sliding_window_decode_matches_forward():
    """A sliding-window model's greedy KV-cache decode equals full-forward
    re-runs — the cache masking honors the window. The window (4) is
    smaller than prompt+generated length, so it actually binds."""
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    cfg = get_model_config("tiny", sliding_window=4)
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(11)
    lens = [6, 4]
    width = 7
    ids = np.zeros((2, width), np.int32)
    mask = np.zeros((2, width), np.int32)
    for i, L in enumerate(lens):
        ids[i, :L] = rs.randint(1, 100, (L,))
        mask[i, :L] = 1
    ids, mask = jnp.asarray(ids), jnp.asarray(mask)
    n_new = 4

    logits, cache = model.start_decode(params, ids, mask, n_new)
    got = []
    for _ in range(n_new):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        got.append(np.asarray(tok))
        logits, cache = model.decode_step(params, cache, tok)
    got = np.stack(got, axis=1)  # [B, n_new]

    want = np.zeros_like(got)
    for i, L in enumerate(lens):
        seq = list(np.asarray(ids[i, :L]))
        for s in range(n_new):
            arr = jnp.asarray(np.asarray(seq)[None, :], jnp.int32)
            full = model.apply(params, arr)
            nxt = int(np.argmax(np.asarray(full[0, -1])))
            want[i, s] = nxt
            seq.append(nxt)
    np.testing.assert_array_equal(got, want)


def test_model_sliding_window_sharded_matches_single_device():
    """The window threads through the shard_map-wrapped flash path: a
    windowed model's sharded forward equals its single-device forward."""
    import jax
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.parallel.sharding import sharding_tree

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, model=2, sequence=1))
    cfg = get_model_config("tiny-gqa", attention="flash", sliding_window=6)
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(3)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)

    want = model.apply(params, ids)
    with jax.sharding.set_mesh(mesh):
        sharded = jax.device_put(
            params, sharding_tree(model.partition_specs(), mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-4)
