"""Flash-attention kernel: numerical parity with the XLA reference
(forward + grads, MHA + GQA), and the model-level backend switch.

Runs the pallas kernel in interpreter mode on CPU; the same code compiles
for TPU."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.ops.attention import causal_attention
from dla_tpu.ops.flash_attention import flash_causal_attention


def _rand_qkv(b, t, h, kh, d, seed=0):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rs.randn(b, t, kh, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, t, kh, d).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("h,kh", [(4, 4), (4, 2)])
def test_flash_matches_xla_forward(h, kh):
    q, k, v = _rand_qkv(2, 16, h, kh, 8)
    got = flash_causal_attention(q, k, v, interpret=True)
    want = causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_multi_block():
    """T larger than one block exercises the online-softmax accumulation."""
    q, k, v = _rand_qkv(1, 32, 2, 2, 8, seed=1)
    got = flash_causal_attention(q, k, v, block_q=8, block_k=8,
                                 interpret=True)
    want = causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_grads_match_xla():
    q, k, v = _rand_qkv(1, 16, 2, 2, 8, seed=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(q, k, v, interpret=True) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_flash_grads_multi_block_gqa():
    """Pallas backward across several q/kv blocks with grouped heads:
    exercises the dQ accumulation, the dK/dV per-q-head kernel, and the
    GQA group-sum."""
    q, k, v = _rand_qkv(2, 48, 4, 2, 8, seed=5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(
            q, k, v, block_q=16, block_k=8, interpret=True) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_model_flash_backend_matches_xla():
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    cfg_x = get_model_config("tiny", attention="xla")
    cfg_f = get_model_config("tiny", attention="flash")
    model_x = Transformer(cfg_x)
    model_f = Transformer(cfg_f)
    params = model_x.init(jax.random.key(0))

    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(1, 100, (2, 16)), jnp.int32)
    mask = jnp.asarray(np.stack([[1] * 16, [1] * 10 + [0] * 6]), jnp.int32)
    out_x = model_x.apply(params, ids, attention_mask=mask)
    out_f = model_f.apply(params, ids, attention_mask=mask)
    # parity on real (unmasked) positions
    np.testing.assert_allclose(
        np.asarray(out_f[0]), np.asarray(out_x[0]), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(out_f[1, :10]), np.asarray(out_x[1, :10]),
        rtol=2e-4, atol=2e-5)


def test_model_flash_backend_packed_falls_back():
    """Packed batches must route to XLA (flash ignores segment masks)."""
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    cfg_f = get_model_config("tiny", attention="flash")
    model = Transformer(cfg_f)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(1)
    a, b = rs.randint(1, 100, (4,)), rs.randint(1, 100, (4,))
    packed = jnp.asarray(np.concatenate([a, b])[None, :], jnp.int32)
    seg = jnp.asarray([[0] * 4 + [1] * 4])
    out_packed = model.apply(params, packed, segment_ids=seg)
    out_a = model.apply(params, jnp.asarray(a[None, :], jnp.int32))
    np.testing.assert_allclose(
        np.asarray(out_packed[0, :4]), np.asarray(out_a[0]),
        rtol=2e-4, atol=2e-5)
