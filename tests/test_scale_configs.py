"""Scale-out config corpus: the 70B / long-context configs must parse,
their meshes must resolve on the target topology, and the batch-size
identity (micro x dp x accum = total, reference README.md:106) must hold."""
import jax

from dla_tpu.parallel.mesh import MeshConfig, build_mesh
from dla_tpu.training.config import load_config


def _check(path: str, n_devices: int):
    cfg = load_config(path)
    mesh_cfg = MeshConfig.from_dict(cfg["hardware"]["mesh"])
    sizes = mesh_cfg.resolve(n_devices)
    assert sum(v > 1 for v in sizes.values()) >= 2, (
        f"{path} should exercise multi-axis sharding, got {sizes}")
    opt = cfg["optimization"]
    dp = sizes["data"] * sizes["fsdp"]
    accum = cfg["hardware"]["gradient_accumulation_steps"]
    assert opt["micro_batch_size"] * dp * accum == opt["total_batch_size"], (
        f"{path}: batch identity violated")
    return cfg, sizes


def test_70b_v5e256_config():
    cfg, sizes = _check("config/sft_llama2_70b_v5e256.yaml", 256)
    assert sizes == {"stage": 1, "data": 1, "fsdp": 32, "model": 8,
                     "sequence": 1, "expert": 1}
    assert cfg["model"]["model_name_or_path"] == "meta-llama/Llama-2-70b-hf"


def test_70b_v5e256_pp_config():
    cfg, sizes = _check("config/sft_llama2_70b_v5e256_pp.yaml", 256)
    assert sizes == {"stage": 4, "data": 1, "fsdp": 8, "model": 8,
                     "sequence": 1, "expert": 1}
    # 80 layers split 4 stages; the configured M must divide the
    # per-step global rows and hit the M >= 4S bubble target with
    # microbatches that still split over the dp shards
    from dla_tpu.ops.pipeline import resolve_microbatches
    opt = cfg["optimization"]
    rows = opt["micro_batch_size"] * sizes["fsdp"] * sizes["data"]
    m = resolve_microbatches(rows, cfg["model"]["pipeline_microbatches"],
                             sizes["stage"], dp_shards=sizes["fsdp"])
    assert m == cfg["model"]["pipeline_microbatches"] == 16
    assert m >= 4 * sizes["stage"]
    assert (rows // m) % sizes["fsdp"] == 0


def test_longcontext_32k_config():
    cfg, sizes = _check("config/sft_longcontext_32k.yaml", 32)
    assert sizes["sequence"] == 8
    assert cfg["model"]["max_seq_length"] == 32768
    assert cfg["model"]["context_parallel"] == "ring"
    # the mistral preset carries sliding_window: 4096; ring CP is
    # window-aware, so this config must construct under a sequence mesh
    # (a blanket window-under-CP refusal would kill the flagship
    # long-context config at model build time)
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    mc = get_model_config(cfg["model"]["model_name_or_path"],
                          context_parallel="ring")
    assert mc.sliding_window == 4096
    mesh = build_mesh(MeshConfig(data=1, fsdp=2, model=1, sequence=4),
                      devices=jax.devices()[:8])
    with jax.sharding.set_mesh(mesh):
        Transformer(mc)  # must not raise


def test_70b_32k_pp_cp_config():
    """The 70B-at-long-context corner: PP x ring CP in one config
    (round-5; stage>1 with sequence>1 was refused before)."""
    cfg = load_config("config/sft_llama2_70b_32k_pp_cp.yaml")
    mesh_cfg = MeshConfig.from_dict(cfg["hardware"]["mesh"])
    sizes = mesh_cfg.resolve(256)
    assert sizes == {"stage": 4, "data": 1, "fsdp": 1, "model": 8,
                     "sequence": 8, "expert": 1}
    assert cfg["model"]["max_seq_length"] == 32768
    assert cfg["model"]["context_parallel"] == "ring"
    # batch identity (dp = 1: all axes go to PP x TP x CP)
    opt = cfg["optimization"]
    assert opt["micro_batch_size"] * 1 * \
        cfg["hardware"]["gradient_accumulation_steps"] == \
        opt["total_batch_size"]
    # M = 16 = 4*stage, bubble 3/19
    from dla_tpu.ops.pipeline import resolve_microbatches
    m = resolve_microbatches(opt["micro_batch_size"],
                             cfg["model"]["pipeline_microbatches"],
                             sizes["stage"], dp_shards=1)
    assert m == 16 >= 4 * sizes["stage"]
    # and the model CONSTRUCTS + runs under a stage x sequence mesh
    # (llama-2 preset at tiny scale keeps construction cheap)
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    mesh = build_mesh(MeshConfig(stage=2, data=1, fsdp=2, model=1,
                                 sequence=2), devices=jax.devices()[:8])
    with jax.sharding.set_mesh(mesh):
        Transformer(get_model_config("tiny", context_parallel="ring"))


def test_70b_mesh_builds_on_virtual_devices():
    # resolve() already validated 256; also build a real (smaller) mesh of
    # the same axis structure on the 8 virtual CPU devices to prove the
    # Mesh constructor accepts the layout.
    mesh = build_mesh(MeshConfig(data=1, fsdp=4, model=2, sequence=1),
                      devices=jax.devices()[:8])
    assert dict(mesh.shape) == {"stage": 1, "data": 1, "fsdp": 4,
                                "model": 2, "sequence": 1, "expert": 1}
