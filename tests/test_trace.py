"""Host tracing, pod aggregation, and SLO watch tests
(docs/OBSERVABILITY.md: Host tracing / Pod-wide aggregation / SLO
watch).

THE pins: (a) a traced CPU train run writes Chrome-trace JSON whose
`step` slices sum to the StepClock wall clock (within 5%), contain an
async-checkpoint `ckpt_write` span on a DIFFERENT thread overlapping a
step, and prefetch slices on the prefetch thread — with
`train_step_compiles` still exactly 1; (b) a disabled tracer does ZERO
producer work (asserted by making the internal `_push` raise); (c) the
serving engine emits one complete async span tree per request whose
event timestamps agree exactly with the recorded TTFT/ITL; (d) the
straggler gauge lights up under injected skew (`simulate_skew` /
DLA_SIM_SKEW) and an SLO burn under a DLA_FAULT_PLAN checkpoint stall
writes `postmortem_slo_burn.json`.
"""
import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dla_tpu.resilience import ENV_VAR as FAULT_ENV
from dla_tpu.telemetry import (
    FlightRecorder,
    Histogram,
    MetricRegistry,
    MetricsHTTPServer,
    PodAggregator,
    ReadinessProbe,
    SkewSimulator,
    SLO,
    SLOWatch,
    StepClock,
    Tracer,
    get_tracer,
    install_tracer,
    is_catalog_name,
)
from dla_tpu.telemetry.trace import _NULL_SPAN
from dla_tpu.utils.logging import latency_summary


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _strict_load(text: str) -> dict:
    """Perfetto's parser is strict JSON: bare NaN/Infinity must fail."""
    def _reject(tok):
        raise ValueError(f"bare {tok} is not strict JSON")
    return json.loads(text, parse_constant=_reject)


def _events(doc, ph=None, name=None, cat=None):
    out = []
    for e in doc["traceEvents"]:
        if ph is not None and e.get("ph") != ph:
            continue
        if name is not None and e.get("name") != name:
            continue
        if cat is not None and e.get("cat") != cat:
            continue
        out.append(e)
    return out


# ---------------------------------------------------------------------------
# tracer core: valid Chrome trace JSON, nesting, ring, off-switch
# ---------------------------------------------------------------------------

def test_tracer_exports_valid_nested_chrome_trace(tmp_path):
    fc = FakeClock()
    tr = Tracer(now=fc, path=str(tmp_path / "trace.json"))
    with tr.span("step", cat="step", step=1):
        fc.advance(0.001)
        with tr.span("compute", cat="step"):
            fc.advance(0.008)
        fc.advance(0.001)
    tr.counter("goodput", 0.8)
    tr.instant("fault", oops=float("nan"))        # sanitized, not bare NaN
    tr.async_begin("request", "request", 7, prompt_tokens=4)
    fc.advance(0.002)
    tr.async_instant("request", "first_token", 7, ttft_ms=2.0)
    tr.async_end("request", "request", 7, status="eos")

    path = tr.dump()
    assert path is not None and path.name == "trace.json"
    doc = _strict_load(path.read_text())
    assert doc["displayTimeUnit"] == "ms"
    assert doc["otherData"]["emitted"] == 7 and doc["otherData"]["dropped"] == 0

    # metadata names the process and the emitting thread
    meta = _events(doc, ph="M")
    assert any(m["name"] == "process_name" for m in meta)
    assert any(m["name"] == "thread_name" for m in meta)

    # positional nesting: the child X event sits inside the parent's span
    parent = _events(doc, ph="X", name="step")[0]
    child = _events(doc, ph="X", name="compute")[0]
    assert parent["ts"] <= child["ts"]
    assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"]
    assert parent["dur"] == pytest.approx(10_000.0)     # 10 ms in us
    assert child["dur"] == pytest.approx(8_000.0)
    assert parent["args"]["step"] == 1
    assert parent["tid"] == child["tid"]

    # counter / instant / async tree shapes
    assert _events(doc, ph="C", name="goodput")[0]["args"]["value"] == 0.8
    assert _events(doc, ph="i", name="fault")[0]["args"]["oops"] is None
    b = _events(doc, ph="b", cat="request")[0]
    n = _events(doc, ph="n", name="first_token")[0]
    e = _events(doc, ph="e", cat="request")[0]
    assert b["id"] == n["id"] == e["id"] == 7
    assert b["ts"] <= n["ts"] <= e["ts"]
    assert e["args"]["status"] == "eos"


def test_tracer_ring_evicts_and_counts_dropped():
    tr = Tracer(capacity=4)
    for i in range(10):
        tr.instant(f"e{i}")
    assert len(tr.events) == 4
    assert tr.emitted == 10
    assert tr.dropped == 6
    names = [e["name"] for e in tr.export()["traceEvents"]
             if e["ph"] == "i"]
    assert names == ["e6", "e7", "e8", "e9"]      # oldest evicted


def test_disabled_tracer_does_zero_work(monkeypatch):
    """THE off-switch pin: every emit path must return before doing ANY
    work when disabled — proven by making the internal _push raise."""
    tr = Tracer(enabled=False)

    def _boom(evt):
        raise AssertionError("disabled tracer did work")

    monkeypatch.setattr(tr, "_push", _boom)
    assert tr.span("x", cat="c", k=1) is _NULL_SPAN   # shared no-op
    with tr.span("x"):
        pass
    tr.complete("x", 0.0, 1.0)
    tr.instant("x")
    tr.counter("x", 1.0)
    tr.async_begin("c", "x", 1)
    tr.async_instant("c", "x", 1)
    tr.async_end("c", "x", 1)
    assert tr.emitted == 0 and tr.dropped == 0


def test_from_config_defaults_and_global_install(tmp_path):
    # no block / enabled:false -> disabled; path defaults under the dir
    assert not Tracer.from_config(None).enabled
    assert not Tracer.from_config({"enabled": False}).enabled
    tr = Tracer.from_config({"enabled": True, "capacity": 16},
                            default_dir=str(tmp_path))
    assert tr.enabled and tr.capacity == 16
    assert tr.path == str(tmp_path / "trace.json")
    # dump with nowhere to write is a safe no-op
    assert Tracer().dump() is None

    # install/get round-trip; None restores the disabled default
    assert not get_tracer().enabled
    try:
        assert install_tracer(tr) is tr
        assert get_tracer() is tr
    finally:
        install_tracer(None)
    assert not get_tracer().enabled


def test_stepclock_feeds_tracer_on_shared_clock():
    fc = FakeClock()
    tr = Tracer(now=fc)
    clock = StepClock(now=fc, tracer=tr)
    with clock.segment("data_wait"):
        fc.advance(0.010)
    with clock.segment("compute"):
        fc.advance(0.080)
    fc.advance(0.010)
    clock.end_step(ok=True, step=3)
    doc = tr.export()
    step = _events(doc, ph="X", name="step")[0]
    assert step["dur"] == pytest.approx(clock.wall_total * 1e6)
    assert step["args"] == {"ok": True, "step": 3}
    seg = _events(doc, ph="X", name="compute")[0]
    assert seg["dur"] == pytest.approx(80_000.0)
    # segment slices nest inside the step slice
    assert step["ts"] <= seg["ts"]
    assert seg["ts"] + seg["dur"] <= step["ts"] + step["dur"]
    good = _events(doc, ph="C", name="goodput")[0]
    assert good["args"]["value"] == pytest.approx(clock.goodput())


def test_profiling_annotations_mirror_into_installed_tracer():
    from dla_tpu.utils.profiling import annotate, step_annotation
    fc = FakeClock()
    tr = Tracer(now=fc)
    install_tracer(tr)
    try:
        with step_annotation(5, name="train"):
            fc.advance(0.004)
            with annotate("my_region"):
                fc.advance(0.002)
    finally:
        install_tracer(None)
    doc = tr.export()
    step = _events(doc, ph="X", name="train_step")[0]
    assert step["args"]["step"] == 5
    region = _events(doc, ph="X", name="my_region", cat="annotate")[0]
    assert region["ts"] >= step["ts"]
    assert region["ts"] + region["dur"] <= step["ts"] + step["dur"]


# ---------------------------------------------------------------------------
# pod aggregation: skew simulator, straggler attribution
# ---------------------------------------------------------------------------

def test_skew_simulator_spec_parsing():
    assert SkewSimulator.from_spec(None) is None
    assert SkewSimulator.from_spec("") is None
    sim = SkewSimulator.from_spec("hosts=8,slow=3,factor=2.5")
    assert (sim.hosts, sim.slow_host, sim.factor) == (8, 3, 2.5)
    sim2 = SkewSimulator.from_spec({"hosts": 4, "slow": 1})
    assert (sim2.hosts, sim2.slow_host, sim2.factor) == (4, 1, 2.0)
    with pytest.raises(ValueError, match="bad DLA_SIM_SKEW field"):
        SkewSimulator.from_spec("hosts=8,turbo=1")
    with pytest.raises(ValueError, match="outside"):
        SkewSimulator.from_spec("hosts=4,slow=4")


def test_pod_aggregator_straggler_and_skew_under_simulated_skew():
    agg = PodAggregator(
        simulate=SkewSimulator(hosts=4, slow_host=2, factor=3.0),
        host_index=0)
    out = agg.update(step_ms=100.0, goodput=0.9)
    for k in out:
        assert is_catalog_name(k), k
    assert out["telemetry/straggler_host"] == 2.0
    assert out["telemetry/pod_step_ms_max"] == pytest.approx(300.0)
    assert out["telemetry/pod_step_ms_min"] == pytest.approx(100.0)
    # skew = max / mean = 300 / 150 = 2.0
    assert out["telemetry/step_skew"] == pytest.approx(2.0)
    assert out["telemetry/pod_goodput_min"] == pytest.approx(0.3)

    # non-zero hosts contribute to the rendezvous but publish nothing
    agg1 = PodAggregator(
        simulate=SkewSimulator(hosts=4, slow_host=2, factor=3.0),
        host_index=1)
    assert agg1.update(100.0, 0.9) == {}
    assert agg1.last.straggler_host == 2     # ...but still computed

    assert PodAggregator(enabled=False, host_index=0).update(1.0, 1.0) == {}


def test_pod_aggregator_single_process_gather_degrades_gracefully():
    agg = PodAggregator(host_index=0)       # real gather path, 1 process
    out = agg.update(step_ms=50.0, goodput=0.7)
    assert out["telemetry/pod_step_ms_max"] == pytest.approx(50.0)
    assert out["telemetry/straggler_host"] == 0.0
    assert out["telemetry/step_skew"] == pytest.approx(1.0)


def test_pod_aggregator_from_config_reads_env(monkeypatch):
    from dla_tpu.telemetry.aggregate import ENV_VAR as SKEW_ENV
    monkeypatch.setenv(SKEW_ENV, "hosts=6,slow=5,factor=4.0")
    agg = PodAggregator.from_config({})
    assert agg.sim is not None and agg.sim.slow_host == 5
    monkeypatch.delenv(SKEW_ENV)
    assert PodAggregator.from_config(None).sim is None


# ---------------------------------------------------------------------------
# SLO watch: burn-rate edge triggering, gauges, postmortem
# ---------------------------------------------------------------------------

def test_slo_validation_and_violation():
    slo = SLO(name="ttft", metric="serving/ttft_ms_p95", objective=500.0)
    assert slo.violated(501.0) and not slo.violated(500.0)
    lo = SLO(name="goodput", metric="telemetry/goodput", objective=0.5,
             kind="min")
    assert lo.violated(0.4) and not lo.violated(0.6)
    with pytest.raises(ValueError, match="kind"):
        SLO(name="x", metric="m", objective=1.0, kind="between")
    with pytest.raises(ValueError, match="budget"):
        SLO(name="x", metric="m", objective=1.0, budget=0.0)


def test_slowatch_burn_edge_trigger_gauges_and_postmortem(tmp_path):
    fc = FakeClock()
    reg = MetricRegistry()
    rec = FlightRecorder(capacity=16, out_dir=str(tmp_path))
    watch = SLOWatch(
        [SLO(name="step_time", metric="telemetry/step_ms",
             objective=100.0, kind="max", window_s=60.0, budget=0.5)],
        registry=reg, recorder=rec, now=fc)

    # healthy: burn 0, ok, no alert
    out = watch.observe({"telemetry/step_ms": 50.0}, step=1)
    assert out["slo/step_time_ok"] == 1.0
    assert out["slo/step_time_burn_rate"] == 0.0
    assert out["slo/step_time_alerts"] == 0.0

    # 1 bad of 2 samples = 50% violating / 50% budget = burn 1.0 -> alert
    fc.advance(1.0)
    out = watch.observe({"telemetry/step_ms": 500.0}, step=2)
    assert out["slo/step_time_burn_rate"] == pytest.approx(1.0)
    assert out["slo/step_time_ok"] == 0.0
    assert out["slo/step_time_alerts"] == 1.0

    # still burning: edge-triggered, no second alert
    fc.advance(1.0)
    out = watch.observe({"telemetry/step_ms": 500.0}, step=3)
    assert out["slo/step_time_alerts"] == 1.0

    # postmortem written with the alert context
    pm = tmp_path / "postmortem_slo_burn.json"
    assert pm.exists()
    doc = _strict_load(pm.read_text())
    assert doc["reason"] == "slo_burn"
    burn_evt = [e for e in doc["events"] if e["kind"] == "slo_burn"][0]
    assert burn_evt["slo"] == "step_time"
    assert burn_evt["metric"] == "telemetry/step_ms"
    assert burn_evt["value"] == 500.0

    # recover: samples age out of the window, burn drops, re-armed
    fc.advance(120.0)
    for _ in range(3):
        fc.advance(1.0)
        out = watch.observe({"telemetry/step_ms": 50.0})
    assert out["slo/step_time_ok"] == 1.0
    # a fresh excursion fires a SECOND alert (re-armed below the line)
    for _ in range(4):
        fc.advance(1.0)
        watch.observe({"telemetry/step_ms": 500.0})
    assert watch._state["step_time"].alerts == 2

    # gauges mirrored into the registry under the slo/ dynamic prefix
    snap = reg.snapshot()
    assert snap["slo/step_time_alerts"] == 2.0
    for k in ("slo/step_time_ok", "slo/step_time_burn_rate"):
        assert k in snap and is_catalog_name(k)


def test_slowatch_from_config_and_absent_metric():
    watch = SLOWatch.from_config({
        "window_s": 30.0, "budget": 0.1,
        "objectives": [
            {"name": "TTFT p95!", "metric": "serving/ttft_ms_p95",
             "objective": 250.0},
            {"metric": "telemetry/goodput", "objective": 0.5,
             "kind": "min", "budget": 0.2},
        ]})
    assert [s.name for s in watch.slos] == ["ttft_p95", "telemetry_goodput"]
    assert watch.slos[0].window_s == 30.0 and watch.slos[0].budget == 0.1
    assert watch.slos[1].budget == 0.2
    # a snapshot missing the metric is simply not sampled that round
    out = watch.observe({"telemetry/goodput": 0.9})
    assert out["slo/ttft_p95_burn_rate"] == 0.0
    assert SLOWatch.from_config(None) is None
    assert SLOWatch.from_config({"objectives": []}) is None


# ---------------------------------------------------------------------------
# satellites: p99 everywhere, /healthz readiness, metrics_diff
# ---------------------------------------------------------------------------

def test_p99_in_latency_summary_histogram_and_prometheus():
    xs = list(range(1, 101))
    s = latency_summary(xs, prefix="ttft_ms_")
    assert s["ttft_ms_p99"] >= s["ttft_ms_p95"] >= s["ttft_ms_p50"]

    h = Histogram()
    for v in xs:
        h.record(float(v))
    hs = h.summary()
    assert hs["p99"] >= hs["p95"]
    assert hs["p99"] == pytest.approx(np.percentile(xs, 99), rel=0.05)

    reg = MetricRegistry()
    hh = reg.histogram("serving/ttft_ms")
    for v in xs:
        hh.record(float(v))
    snap = reg.snapshot()
    assert is_catalog_name("serving/ttft_ms_p99")
    assert snap["serving/ttft_ms_p99"] >= snap["serving/ttft_ms_p95"]
    text = reg.prometheus_text()
    assert 'dla_serving_ttft_ms{quantile="0.99"}' in text


def test_healthz_readiness_flips_to_503_on_staleness():
    fc = FakeClock()
    probe = ReadinessProbe(threshold_s=10.0, now=fc)
    assert probe.ready and probe.age_s == 0.0
    srv = MetricsHTTPServer(MetricRegistry(), port=0, readiness=probe)
    try:
        health = srv.url.replace("/metrics", "/healthz")
        fc.advance(3.0)
        with urllib.request.urlopen(health, timeout=5) as resp:
            assert resp.status == 200
            assert resp.read() == b"ok age_s=3.0\n"
        fc.advance(20.0)                 # stale: no beat for 23 s
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(health, timeout=5)
        assert exc_info.value.code == 503
        body = exc_info.value.read()
        assert body.startswith(b"stale age_s=23.0")
        assert b"threshold_s=10.0" in body
        probe.beat()                     # a completed step recovers it
        with urllib.request.urlopen(health, timeout=5) as resp:
            assert resp.status == 200
    finally:
        srv.stop()


def test_metrics_diff_detects_regressions_with_tolerance(tmp_path,
                                                         capsys):
    from tools.metrics_diff import main
    base = tmp_path / "base.json"
    cand = tmp_path / "cand.json"
    base.write_text(json.dumps({
        "telemetry": {"step_ms": 100.0, "goodput": 0.8},
        "tokens_per_sec_per_chip": 1000.0, "notes": "ignored"}))
    cand.write_text(json.dumps({
        "telemetry": {"step_ms": 130.0, "goodput": 0.82},
        "tokens_per_sec_per_chip": 1010.0}))

    # step_ms +30% against its good direction -> regression, exit 1
    assert main([str(base), str(cand)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "telemetry/step_ms" in out

    # a per-metric tolerance waives exactly that regression
    assert main([str(base), str(cand),
                 "--tolerance-for", "telemetry/step_ms=0.5"]) == 0

    # Prometheus-text inputs: quantile-labeled series compare too
    bt = tmp_path / "base.txt"
    ct = tmp_path / "cand.txt"
    bt.write_text('dla_serving_ttft_ms{quantile="0.95"} 50.0\n')
    ct.write_text('dla_serving_ttft_ms{quantile="0.95"} 80.0\n')
    assert main([str(bt), str(ct)]) == 1
    assert main([str(bt), str(bt)]) == 0

    # disjoint snapshots: clean by default, a failure when required
    other = tmp_path / "other.json"
    other.write_text(json.dumps({"something_else": 1.0}))
    assert main([str(base), str(other)]) == 0
    assert main([str(base), str(other), "--require-common"]) == 1

    # unreadable input -> usage error, exit 2
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert main([str(bad), str(cand)]) == 2


# ---------------------------------------------------------------------------
# trainer integration: THE acceptance trace on mesh8
# ---------------------------------------------------------------------------

DIM = 8


def _make_batch(i, bs=8):
    rs = np.random.RandomState(4000 + i)
    x = rs.normal(size=(bs, DIM)).astype(np.float32)
    w_true = np.arange(1, DIM + 1, dtype=np.float32)
    return {"x": x, "y": (x @ w_true).astype(np.float32)}


class BatchIter:
    def __init__(self):
        self.i = 0

    def __iter__(self):
        return self

    def __next__(self):
        b = _make_batch(self.i)
        self.i += 1
        return b

    def state_dict(self):
        return {"i": self.i}

    def load_state_dict(self, state):
        self.i = int(state["i"])


def _linreg_loss(params, frozen, batch, rng):
    del frozen, rng
    pred = batch["x"] @ params["w"]
    return jnp.mean((pred - batch["y"]) ** 2), {}


def _make_trainer(mesh, out_dir, *, max_steps=6, save_every=0,
                  log_every=10 ** 6, prefetch=0, telemetry=None,
                  resilience=None, slo=None):
    from dla_tpu.training.trainer import Trainer
    logging_cfg = {"output_dir": str(out_dir), "log_dir": None,
                   "save_every_steps": save_every,
                   "log_every_steps": log_every}
    if telemetry is not None:
        logging_cfg["telemetry"] = telemetry
    config = {
        "experiment_name": "trace_test",
        "data": {"prefetch": prefetch},
        "optimization": {"total_batch_size": 8, "micro_batch_size": 1,
                         "learning_rate": 1e-2, "max_train_steps": max_steps,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": logging_cfg,
        "hardware": {"gradient_accumulation_steps": 2},
    }
    if resilience is not None:
        config["resilience"] = resilience
    if slo is not None:
        config["slo"] = slo
    return Trainer(config=config, mesh=mesh, loss_fn=_linreg_loss,
                   params={"w": jnp.zeros((DIM,), jnp.float32)},
                   param_specs={"w": P()})


def test_traced_train_run_writes_consistent_chrome_trace(mesh8, tmp_path,
                                                         monkeypatch):
    """THE acceptance pin: a CPU run with tracing enabled writes a
    Chrome-trace JSON whose step slices sum to the StepClock wall clock
    (within 5%), shows the async-checkpoint writer span on a different
    thread overlapping a step slice, and carries prefetch slices — with
    the train step still compiling exactly once."""
    trace_path = tmp_path / "trace.json"
    with jax.sharding.set_mesh(mesh8):
        # an injected io_error makes the background write retry with
        # backoff, so the writer-thread span provably overlaps steps
        monkeypatch.setenv(FAULT_ENV, "step=2:io_error")
        tr = _make_trainer(
            mesh8, tmp_path / "run", max_steps=6, save_every=2,
            prefetch=2,
            telemetry={"trace": {"enabled": True,
                                 "path": str(trace_path)}},
            resilience={"async_checkpointing": True, "save_retries": 3,
                        "retry_backoff_s": 0.4})
        try:
            assert tr.tracer.enabled
            assert get_tracer() is tr.tracer      # installed process-wide
            it = BatchIter()
            tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
            tr.checkpointer.wait()
        finally:
            install_tracer(None)
        assert tr.step == 6
        assert tr.train_step_compiles == 1        # tracing adds no compiles

        assert trace_path.exists()
        doc = _strict_load(trace_path.read_text())

        # step slices sum to the clock's wall total within 5%
        steps = _events(doc, ph="X", name="step")
        assert len(steps) == 6
        traced_s = sum(e["dur"] for e in steps) / 1e6
        assert traced_s == pytest.approx(tr.clock.wall_total, rel=0.05)
        step_tids = {e["tid"] for e in steps}
        assert len(step_tids) == 1                # all on the trainer thread

        # segment slices (data_wait/h2d/compute/...) nest under steps
        computes = _events(doc, ph="X", name="compute")
        assert len(computes) == 6
        assert all(e["tid"] in step_tids for e in computes)

        # the async-checkpoint writer span runs on a DIFFERENT thread
        # and overlaps at least one step slice — overlap made visible
        writes = _events(doc, ph="X", name="ckpt_write")
        assert writes, "no ckpt_write span from the writer thread"
        assert all(w["tid"] not in step_tids for w in writes)
        overlaps = any(
            w["ts"] < s["ts"] + s["dur"] and s["ts"] < w["ts"] + w["dur"]
            for w in writes for s in steps)
        assert overlaps, "checkpoint write never overlapped a step"

        # prefetch slices from the prefetch thread
        pf = _events(doc, ph="X", name="prefetch_next")
        assert pf and all(e["tid"] not in step_tids for e in pf)

        # goodput counter track sampled once per step
        assert len(_events(doc, ph="C", name="goodput")) == 6

        # tracer accounting rides the registry
        snap = tr.registry.snapshot()
        assert snap["telemetry/trace_events"] == float(tr.tracer.emitted)
        assert snap["telemetry/trace_dropped"] == 0.0


def test_untraced_train_run_emits_zero_events(mesh8, tmp_path):
    """Acceptance pin: tracing disabled (the default) means ZERO events
    pushed by any producer — not 'few', none."""
    with jax.sharding.set_mesh(mesh8):
        tr = _make_trainer(mesh8, tmp_path / "run", max_steps=4,
                           prefetch=2, save_every=2,
                           resilience={"async_checkpointing": True})
        it = BatchIter()
        tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        tr.checkpointer.wait()
        assert tr.step == 4
        assert not tr.tracer.enabled
        assert tr.tracer.emitted == 0
        assert not (tmp_path / "run" / "trace.json").exists()


def test_trainer_straggler_gauge_under_simulated_skew(mesh8, tmp_path):
    """The pod-aggregation path end to end on one CPU process: the
    configured skew simulation lights up the straggler gauge on the
    trainer's own /metrics registry."""
    with jax.sharding.set_mesh(mesh8):
        tr = _make_trainer(
            mesh8, tmp_path / "run", max_steps=4, log_every=2,
            telemetry={"aggregate": {
                "simulate_skew": "hosts=4,slow=2,factor=3.0"}})
        it = BatchIter()
        tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        snap = tr.registry.snapshot()
        assert snap["telemetry/straggler_host"] == 2.0
        assert snap["telemetry/step_skew"] == pytest.approx(2.0)
        assert snap["telemetry/pod_step_ms_max"] == pytest.approx(
            3.0 * snap["telemetry/pod_step_ms_min"], rel=1e-6)


def test_slo_burn_fires_under_injected_checkpoint_stall(mesh8, tmp_path,
                                                        monkeypatch):
    """Satellite pin: a DLA_FAULT_PLAN checkpoint stall drags goodput
    under a declared SLO; the burn alert lands in the flight recorder
    AND as a postmortem_slo_burn.json."""
    with jax.sharding.set_mesh(mesh8):
        out = tmp_path / "run"
        monkeypatch.setenv(FAULT_ENV, "step=2:io_error")
        tr = _make_trainer(
            mesh8, out, max_steps=6, save_every=2, log_every=2,
            resilience={"async_checkpointing": True, "save_retries": 3,
                        "retry_backoff_s": 0.4},
            slo={"objectives": [
                {"name": "goodput", "metric": "telemetry/goodput",
                 "objective": 0.999, "kind": "min", "budget": 0.01}]})
        it = BatchIter()
        tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        tr.checkpointer.wait()

        assert tr.slo is not None
        assert tr.slo._state["goodput"].alerts >= 1
        snap = tr.registry.snapshot()
        assert snap["slo/goodput_ok"] == 0.0
        assert snap["slo/goodput_alerts"] >= 1.0

        pm = out / "postmortem_slo_burn.json"
        assert pm.exists()
        doc = _strict_load(pm.read_text())
        assert doc["reason"] == "slo_burn"
        kinds = [e["kind"] for e in doc["events"]]
        assert "slo_burn" in kinds


# ---------------------------------------------------------------------------
# serving: one async span tree per request, consistent with TTFT/ITL
# ---------------------------------------------------------------------------

def test_serving_request_span_tree_matches_recorded_latencies(tmp_path):
    """Acceptance pin: the trace contains at least one COMPLETE request
    span tree (begin -> admitted -> first_token -> decode... -> end) and
    the span timestamps agree exactly with the engine's recorded
    request times — the tracer shares the engine's clock."""
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.serving import ServingConfig, ServingEngine

    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    gen = GenerationConfig(max_new_tokens=5, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    trace_path = tmp_path / "serve_trace.json"
    eng = ServingEngine(model, params, gen, ServingConfig(
        page_size=4, num_pages=32, num_slots=2, max_model_len=32,
        max_prefill_batch=2,
        trace={"enabled": True, "path": str(trace_path)}))
    try:
        assert eng.tracer.enabled and get_tracer() is eng.tracer
        rs = np.random.RandomState(5)
        rids = [eng.submit(list(rs.randint(3, 500, (4,))), 5)
                for _ in range(3)]
        eng.run_until_drained(max_steps=500)
        reqs = {rid: eng.result(rid) for rid in rids}
    finally:
        eng.close()
    # close() dumped the trace and restored the disabled global tracer
    assert not get_tracer().enabled
    assert trace_path.exists()
    doc = _strict_load(trace_path.read_text())

    complete_trees = 0
    for rid, req in reqs.items():
        begins = [e for e in _events(doc, ph="b", cat="request")
                  if e["id"] == rid]
        ends = [e for e in _events(doc, ph="e", cat="request")
                if e["id"] == rid]
        insts = [e for e in _events(doc, ph="n", cat="request")
                 if e["id"] == rid]
        if not (begins and ends):
            continue
        complete_trees += 1
        b, e = begins[0], ends[0]
        assert b["args"]["prompt_tokens"] == 4
        assert e["args"]["status"] in ("eos", "length")
        assert e["args"]["tokens"] == len(req.generated)
        assert b["ts"] <= e["ts"]

        admitted = [i for i in insts if i["name"] == "admitted"]
        first = [i for i in insts if i["name"] == "first_token"]
        decodes = [i for i in insts if i["name"] == "decode"]
        assert admitted and first
        # TTFT: the gap between the begin and first_token events IS the
        # recorded ttft_ms — same clock, no drift allowed
        ttft_from_trace = (first[0]["ts"] - b["ts"]) / 1000.0
        recorded = (req.first_token_time - req.arrival_time) * 1000.0
        assert ttft_from_trace == pytest.approx(recorded, abs=1e-6)
        assert first[0]["args"]["ttft_ms"] == pytest.approx(recorded)
        # decode instants are ordered and carry per-token ITL
        last_ts = first[0]["ts"]
        for d in sorted(decodes, key=lambda x: x["ts"]):
            assert d["ts"] >= last_ts
            assert d["args"]["itl_ms"] >= 0.0
            last_ts = d["ts"]
    assert complete_trees >= 1


def test_serving_timeout_and_drain_close_their_span_trees():
    """Requests that never finish normally still get their async end:
    timeout and drain-cancel both close the tree with a status."""
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.serving import ServingConfig, ServingEngine

    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    gen = GenerationConfig(max_new_tokens=5, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    eng = ServingEngine(model, params, gen, ServingConfig(
        page_size=4, num_pages=32, num_slots=2, max_model_len=32,
        max_prefill_batch=2, trace={"enabled": True}))
    try:
        rid = eng.submit([5, 6, 7], 5)
        eng.begin_drain()          # queued, no tokens -> cancelled
        ends = [e for e in eng.tracer.events
                if e.get("ph") == "e" and e.get("id") == rid]
        assert ends and ends[0]["args"]["status"] == "cancelled"
    finally:
        eng.close()
    assert not get_tracer().enabled
