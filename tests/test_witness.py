"""Runtime lock witness (dla_tpu/analysis/witness.py).

THE pins: (a) a provoked two-lock order inversion IS detected — the
cycle check is proven live, not assumed; (b) consistent ordering (and
re-entrant RLock acquires) record no cycle; (c) a detected cycle dumps
the flight-recorder-shaped ``postmortem_lock_cycle.json`` that
tools/dla_doctor.py ranks; (d) installation is idempotent and scoped —
locks created outside the scope roots stay raw primitives; (e)
attribute watching records per-thread accessor names. The witness is
also installed for the whole tier-1 run by tests/conftest.py, so every
concurrency-heavy test doubles as a lock-order probe.
"""
import json
import threading

from dla_tpu.analysis.witness import (
    LockWitness,
    WitnessedLock,
    WitnessedRLock,
    get_witness,
    install_witness,
    unwatch_all,
    watch_attributes,
)


def _cycle_pair(w):
    a = WitnessedLock(w, name="lock-a")
    b = WitnessedLock(w, name="lock-b")
    return a, b


# ------------------------------------------------------- cycle detection

def test_provoked_two_lock_cycle_is_detected(tmp_path):
    w = LockWitness()
    a, b = _cycle_pair(w)
    with a:
        with b:
            pass
    with b:                        # the inversion: b then a
        with a:
            pass
    cycles = w.check(str(tmp_path))
    assert cycles == [["lock-a", "lock-b", "lock-a"]]
    doc = json.loads((tmp_path / "postmortem_lock_cycle.json").read_text())
    assert doc["reason"] == "lock_cycle"
    assert doc["cycles"] == [["lock-a", "lock-b", "lock-a"]]
    edges = {(e["frm"], e["to"]) for e in doc["events"]}
    assert ("lock-a", "lock-b") in edges and ("lock-b", "lock-a") in edges


def test_consistent_order_is_clean(tmp_path):
    w = LockWitness()
    a, b = _cycle_pair(w)
    for _ in range(3):
        with a:
            with b:
                pass
    assert w.check(str(tmp_path)) == []
    assert not (tmp_path / "postmortem_lock_cycle.json").exists()


def test_cross_thread_inversion_is_detected():
    """The real deadlock shape: each order taken on a different
    thread (neither thread alone ever inverts)."""
    w = LockWitness()
    a, b = _cycle_pair(w)

    def fwd():
        with a:
            with b:
                pass

    t = threading.Thread(target=fwd, name="dla-test-fwd")
    t.start()
    t.join()
    with b:
        with a:
            pass
    assert w.cycles() == [["lock-a", "lock-b", "lock-a"]]
    threads = {e["thread"] for e in w.edges.values()}
    assert threads == {"dla-test-fwd", "MainThread"}


def test_reentrant_rlock_records_no_self_edge():
    w = LockWitness()
    r = WitnessedRLock(w, name="rlock")
    other = WitnessedLock(w, name="other")
    with r:
        with r:                    # re-entry: no rlock->rlock edge
            with other:
                pass
        assert not r.locked() or True   # still held by us
    assert ("rlock", "rlock") not in w.edges
    assert ("rlock", "other") in w.edges
    assert w.cycles() == []


def test_release_unwinds_held_stack():
    w = LockWitness()
    a, b = _cycle_pair(w)
    a.acquire()
    a.release()
    b.acquire()                    # a no longer held: no a->b edge
    b.release()
    assert w.edges == {}


# --------------------------------------------------- install / uninstall

def test_install_is_idempotent_and_scoped(tmp_path):
    # conftest installs the witness session-wide; install again must
    # hand back the SAME live witness, not reset state
    w1 = install_witness()
    assert install_witness() is w1 and get_witness() is w1
    # locks created from repo files are witnessed...
    lk = threading.Lock()
    assert isinstance(lk, WitnessedLock)
    with lk:
        pass
    # ...while stdlib-internal creations stay raw: an Event's lock is
    # allocated inside threading.py, far outside the scope roots
    ev = threading.Event()
    assert not isinstance(ev._cond._lock, WitnessedLock)


def test_witnessed_lock_supports_condition_protocol():
    # Condition wraps a caller-supplied lock and probes ownership via
    # acquire(False)/release — the wrapper must duck-type all of it
    cond = threading.Condition(threading.Lock())
    with cond:
        cond.notify_all()


# ----------------------------------------------------- attribute watching

def test_watch_attributes_records_accessor_threads():
    w = LockWitness()

    class Box:
        def __init__(self):
            self.count = 0

    try:
        watch_attributes(Box, ["count"], w)
        box = Box()

        def bump():
            box.count += 1

        t = threading.Thread(target=bump, name="dla-test-bump")
        t.start()
        t.join()
        box.count += 1
        acc = w.attr_threads["Box"]["count"]
        assert "write:dla-test-bump" in acc
        assert "read:MainThread" in acc and "write:MainThread" in acc
    finally:
        unwatch_all()
    # restored: no further recording
    before = len(w.attr_threads["Box"]["count"])
    Box().count = 5
    assert len(w.attr_threads["Box"]["count"]) == before
