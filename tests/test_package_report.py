"""Packaging phase CLI: metrics plots + eval artifacts + samples into
one report directory (reference README.md:46's phase 6, which shipped no
code)."""
import json

from dla_tpu.eval.package_report import main, read_metrics, write_report


def _write_metrics(path, n=20):
    with path.open("w") as fh:
        for s in range(1, n + 1):
            fh.write(json.dumps({
                "step": s, "time": 1000.0 + s,
                "train/loss": 5.0 / s,
                "tokens_per_sec_per_chip": 100.0 + s}) + "\n")
        fh.write("{torn line")  # killed-run tail must not break parsing


def test_report_end_to_end(tmp_path):
    metrics = tmp_path / "metrics.jsonl"
    _write_metrics(metrics)

    eval_dir = tmp_path / "eval"
    eval_dir.mkdir()
    (eval_dir / "results.json").write_text(json.dumps({
        "base": {"helpfulness": {"avg_length": 12.5, "refusal_rate": 0.1,
                                 "toxicity_proxy": 0.0},
                 "wikitext": {"perplexity": 17.25, "nll": 2.848,
                              "n_tokens": 4096}}}))
    (eval_dir / "summary.md").write_text("| col |\n|---|\n")
    (eval_dir / "latency.json").write_text(json.dumps(
        {"results": [{"batch": 1, "tokens_per_second": 100.0}]}))

    samples = tmp_path / "rollouts.jsonl"
    with samples.open("w") as fh:
        fh.write(json.dumps({"prompt": "hi", "teacher_response": "hello",
                             "reward": 0.5}) + "\n")

    out = tmp_path / "report"
    report = write_report(metrics, eval_dir, samples, out)
    text = report.read_text()
    assert "train/loss" in text
    assert "helpfulness" in text
    assert "samples.md" in text
    # perplexity benchmarks get their own table, not None-celled rows in
    # the heuristics table (round-3 advisor finding)
    assert "17.25" in text and "wikitext" in text
    assert "None" not in text
    assert (out / "metrics_train_loss.png").is_file()
    assert (out / "metrics_tokens_per_sec_per_chip.png").is_file()
    assert "hello" in (out / "samples.md").read_text()


def test_read_metrics_skips_torn_lines(tmp_path):
    metrics = tmp_path / "m.jsonl"
    _write_metrics(metrics, n=3)
    rows = read_metrics(metrics)
    assert len(rows) == 3 and rows[-1]["step"] == 3


def test_cli(tmp_path):
    metrics = tmp_path / "metrics.jsonl"
    _write_metrics(metrics, n=5)
    out = tmp_path / "rep"
    main(["--metrics", str(metrics), "--output", str(out),
          "--title", "smoke"])
    assert (out / "report.md").read_text().startswith("# smoke")
