"""Eval suite tests: heuristic math, artifact formats, latency harness."""
import json

import numpy as np
import yaml

from dla_tpu.data.jsonl import write_jsonl
from dla_tpu.eval.eval_alignment import load_prompts, summarize_responses


def test_summarize_responses_reference_math():
    responses = [
        "Sorry, I cannot help with that.",   # refusal
        "Here is a friendly answer.",
        "The weapon was historic.",          # toxicity proxy
        "",
    ]
    m = summarize_responses(responses)
    assert m["refusal_rate"] == 0.25
    assert m["toxicity_proxy"] == 0.25
    want_len = np.mean([6, 5, 4, 0])
    assert abs(m["avg_length"] - want_len) < 1e-9
    empty = summarize_responses([])
    assert empty == {"avg_length": 0.0, "refusal_rate": 0.0,
                     "toxicity_proxy": 0.0}


def test_load_prompts_alt_keys(tmp_path):
    write_jsonl(tmp_path / "p.jsonl", [
        {"prompt": "a"}, {"question": "b"}, {"instruction": "c"},
        {"other": "d"}])
    prompts = load_prompts({"type": "local",
                            "prompts_path": str(tmp_path / "p.jsonl")}, None)
    assert prompts == ["a", "b", "c"]
    # subsampling is deterministic per seed
    s1 = load_prompts({"type": "local",
                       "prompts_path": str(tmp_path / "p.jsonl")}, 2, seed=1)
    s2 = load_prompts({"type": "local",
                       "prompts_path": str(tmp_path / "p.jsonl")}, 2, seed=1)
    assert s1 == s2 and len(s1) == 2


def test_eval_alignment_end_to_end(tmp_path):
    from dla_tpu.eval.eval_alignment import main
    write_jsonl(tmp_path / "prompts.jsonl",
                [{"prompt": f"question {i}"} for i in range(4)])
    cfg = {
        "seed": 0,
        "models": {"base": "tiny"},
        "model": {"tokenizer": "byte"},
        "benchmarks": {
            "local_bench": {"type": "local",
                            "prompts_path": str(tmp_path / "prompts.jsonl"),
                            "max_samples": 3},
        },
        "generation": {"max_new_tokens": 4, "temperature": 0.7,
                       "top_p": 0.9, "do_sample": True, "batch_size": 2,
                       "max_prompt_length": 24},
        "logging": {"output_path": str(tmp_path / "out" / "results.json"),
                    "table_path": str(tmp_path / "out" / "summary.md")},
    }
    p = tmp_path / "eval.yaml"
    p.write_text(yaml.safe_dump(cfg))
    main(["--config", str(p)])

    results = json.loads((tmp_path / "out" / "results.json").read_text())
    assert set(results) == {"base"}
    m = results["base"]["local_bench"]
    assert set(m) == {"avg_length", "refusal_rate", "toxicity_proxy"}
    table = (tmp_path / "out" / "summary.md").read_text()
    assert table.startswith("| Model | Benchmark | Avg Len |")
    assert "| base | local_bench |" in table


def test_eval_latency_end_to_end(tmp_path):
    from dla_tpu.eval.eval_latency import main
    cfg = {
        "seed": 0,
        "models": {"tiny": "tiny"},
        "model": {"tokenizer": "byte"},
        "latency": {
            "hardware": "cpu-test",
            "batch_sizes": [1, 2],
            "seq_lengths": [16],
            "warmup_steps": 1,
            "measure_steps": 2,
            "decode": {"enabled": True, "batch_size": 2,
                       "prompt_length": 8, "new_tokens": 4},
        },
        "logging": {"output_path": str(tmp_path / "out" / "results.json")},
    }
    p = tmp_path / "eval.yaml"
    p.write_text(yaml.safe_dump(cfg))
    main(["--config", str(p)])
    lat = json.loads((tmp_path / "out" / "latency.json").read_text())
    assert lat["hardware"] == "cpu-test"
    rows = lat["tiny"]["forward"]
    assert len(rows) == 2
    assert all(r["tokens_per_second"] > 0 and r["latency_ms"] > 0
               for r in rows)
    dec = lat["tiny"]["decode"]
    assert dec["decode_tokens_per_second"] > 0


def test_eval_latency_serving_mode(tmp_path):
    """--serving runs the continuous-batching engine on a Poisson
    arrival trace and reports per-request TTFT/ITL percentiles."""
    from dla_tpu.eval.eval_latency import main
    cfg = {
        "seed": 0,
        "models": {"tiny": "tiny"},
        "model": {"tokenizer": "byte"},
        "latency": {
            "hardware": "cpu-test",
            "batch_sizes": [1],
            "seq_lengths": [16],
            "warmup_steps": 0,
            "measure_steps": 1,
            "decode": {"enabled": False},
            "serving": {"num_requests": 3, "arrival_rate": 200.0,
                        "new_tokens": 4, "prompt_len_min": 4,
                        "prompt_len_max": 8, "page_size": 4,
                        "num_pages": 32, "num_slots": 2,
                        "max_model_len": 32},
        },
        "logging": {"output_path": str(tmp_path / "out" / "results.json")},
    }
    p = tmp_path / "eval.yaml"
    p.write_text(yaml.safe_dump(cfg))
    main(["--config", str(p), "--serving"])
    lat = json.loads((tmp_path / "out" / "latency.json").read_text())
    srv = lat["tiny"]["serving"]
    assert srv["num_requests"] == 3
    assert srv["requests_per_second"] > 0
    for k in ("ttft_ms_p50", "ttft_ms_p95", "itl_ms_p50", "itl_ms_p95"):
        assert srv[k] >= 0.0
    assert srv["ttft_ms_p95"] >= srv["ttft_ms_p50"]
    assert srv["serve_tokens_per_second"] > 0


def test_eval_perplexity_benchmark(tmp_path):
    """benchmark type: perplexity — token-mean NLL over {prompt,response}
    pairs through the fused CE path, folded into results.json/summary.md."""
    from dla_tpu.eval.eval_alignment import main
    write_jsonl(tmp_path / "ppl.jsonl",
                [{"prompt": f"question {i}", "response": f"answer {i}"}
                 for i in range(5)])
    write_jsonl(tmp_path / "prompts.jsonl",
                [{"prompt": "hello"} for _ in range(2)])
    cfg = {
        "seed": 0,
        "models": {"base": "tiny"},
        "model": {"tokenizer": "byte"},
        "benchmarks": {
            "gen_bench": {"type": "local",
                          "prompts_path": str(tmp_path / "prompts.jsonl")},
            "heldout_ppl": {"type": "perplexity",
                            "path": str(tmp_path / "ppl.jsonl"),
                            "max_seq_length": 48},
        },
        "generation": {"max_new_tokens": 4, "batch_size": 2,
                       "max_prompt_length": 24},
        "logging": {"output_path": str(tmp_path / "out" / "results.json"),
                    "table_path": str(tmp_path / "out" / "summary.md")},
    }
    p = tmp_path / "eval.yaml"
    p.write_text(yaml.safe_dump(cfg))
    main(["--config", str(p)])

    results = json.loads((tmp_path / "out" / "results.json").read_text())
    m = results["base"]["heldout_ppl"]
    assert m["n_tokens"] > 0
    assert np.isfinite(m["nll"]) and m["perplexity"] > 1.0
    table = (tmp_path / "out" / "summary.md").read_text()
    assert "Perplexity" in table and "heldout_ppl" in table
    # the generation benchmark still renders in the heuristics table
    assert "| base | gen_bench |" in table
