"""Mixture-of-Experts (ops/moe.py + transformer integration): routing
parity with a per-token dense reference, expert-parallel mesh parity,
aux-loss plumbing into the fused CE, end-to-end training, and the
KV-cache decode path. Beyond-reference capability (the reference is
dense-only, SURVEY.md sec 2.3 EP row) that makes the reserved `expert`
mesh axis real."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.models.config import ModelConfig, get_model_config
from dla_tpu.models.transformer import Transformer
from dla_tpu.ops.fused_ce import model_fused_ce
from dla_tpu.ops.moe import expert_capacity, moe_mlp
from dla_tpu.parallel.mesh import MeshConfig, build_mesh
from dla_tpu.parallel.sharding import sharding_tree


def _moe_weights(seed=0, d=6, f=10, e=4):
    rs = np.random.RandomState(seed)
    return (jnp.asarray(rs.randn(d, e).astype(np.float32) * 0.1),
            jnp.asarray(rs.randn(e, d, f).astype(np.float32) * 0.2),
            jnp.asarray(rs.randn(e, d, f).astype(np.float32) * 0.2),
            jnp.asarray(rs.randn(e, f, d).astype(np.float32) * 0.2))


@pytest.mark.parametrize("k", [1, 2])
def test_moe_matches_dense_per_token_reference(k):
    """With unlimited capacity, routed output == looping over each
    token's top-k experts with renormalized softmax weights."""
    rs = np.random.RandomState(1)
    b, t, d, f, e = 2, 8, 6, 10, 4
    h = jnp.asarray(rs.randn(b, t, d).astype(np.float32))
    rw, wg, wu, wd = _moe_weights(d=d, f=f, e=e)
    got, aux = moe_mlp(h, rw, wg, wu, wd, k=k, capacity_factor=100.0)
    logits = np.asarray(h @ rw)
    want = np.zeros((b, t, d), np.float32)
    for bi in range(b):
        for ti in range(t):
            idx = np.argsort(-logits[bi, ti])[:k]
            w = np.exp(logits[bi, ti][idx] - logits[bi, ti][idx].max())
            w /= w.sum()
            for j, ei in enumerate(idx):
                x = np.asarray(h)[bi, ti]
                gate = x @ np.asarray(wg)[ei]
                up = x @ np.asarray(wu)[ei]
                act = gate / (1 + np.exp(-gate)) * up
                want[bi, ti] += w[j] * (act @ np.asarray(wd)[ei])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)
    assert float(aux.dropped_frac) == 0.0


def test_moe_capacity_drops_and_stays_finite():
    rs = np.random.RandomState(2)
    h = jnp.asarray(rs.randn(2, 16, 6).astype(np.float32))
    rw, wg, wu, wd = _moe_weights(seed=3)
    got, aux = moe_mlp(h, rw, wg, wu, wd, k=2, capacity_factor=0.25)
    assert np.isfinite(np.asarray(got)).all()
    assert 0.0 < float(aux.dropped_frac) < 1.0
    assert expert_capacity(16, 4, 2, 0.25) == 2


def test_moe_balance_loss_prefers_uniform():
    """Balanced routing -> load_balance ~= 1; a router that sends every
    token to one expert -> ~E."""
    rs = np.random.RandomState(4)
    # positive inputs so a single positive router column dominates
    h = jnp.asarray(np.abs(rs.randn(2, 32, 6)).astype(np.float32) + 0.5)
    _, wg, wu, wd = _moe_weights(seed=5)
    spread_rw = jnp.asarray(rs.randn(6, 4).astype(np.float32) * 0.01)
    _, aux_u = moe_mlp(h, spread_rw, wg, wu, wd, k=1)
    collapsed_rw = jnp.zeros((6, 4), jnp.float32).at[:, 0].set(10.0)
    _, aux_c = moe_mlp(h, collapsed_rw, wg, wu, wd, k=1)
    assert float(aux_c.load_balance) > 3.5  # ~E when fully collapsed
    assert float(aux_c.load_balance) > float(aux_u.load_balance)


def test_moe_expert_parallel_mesh_parity():
    """expert=2 sharding reproduces the unsharded forward (the dispatch
    einsums become all-to-alls under GSPMD)."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    model = Transformer(get_model_config("tiny-moe"))
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(5)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    want = model.apply(params, ids)
    mesh = build_mesh(MeshConfig(data=1, fsdp=2, model=2, sequence=1,
                                 expert=2))
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_moe_trains_and_aux_regularizes(mesh8):
    """Fused CE + weighted aux losses: loss falls on random labels, and
    the router stays un-collapsed (balance loss near 1 after training)."""
    from dla_tpu.training.trainer import Trainer

    model = Transformer(get_model_config("tiny-moe"))
    params = model.init(jax.random.key(0))

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        loss, _ = model_fused_ce(model, p, batch)
        return loss, {}

    config = {
        "experiment_name": "moe_train_test",
        "optimization": {"total_batch_size": 8, "micro_batch_size": 2,
                         "learning_rate": 5e-3, "max_train_steps": 25,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": "/tmp/moe_train_test", "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    rs = np.random.RandomState(0)
    batch = {"input_ids": rs.randint(1, 100, (8, 16)).astype(np.int32),
             "attention_mask": np.ones((8, 16), np.int32),
             "labels": rs.randint(1, 100, (8, 16)).astype(np.int32)}
    with jax.sharding.set_mesh(mesh8):
        trainer = Trainer(config=config, mesh=mesh8, loss_fn=loss_fn,
                          params=params,
                          param_specs=model.partition_specs())
        losses = [trainer.step_on_batch(batch, jax.random.key(i))[0]
                  for i in range(25)]
    assert losses[-1] < losses[0] - 0.5, (losses[0], losses[-1])
    # router grads flowed (router weights moved from init)
    moved = float(jnp.sum(jnp.abs(
        trainer.params["layers"]["router"]
        - params["layers"]["router"])))
    assert moved > 0.0


def test_moe_decode_matches_forward():
    """KV-cache decode through the routed MLP == slicing the full
    forward (same parity contract the dense decode path has). Capacity
    is raised so nothing drops: token dropping depends on how many other
    tokens share the batch, so the contract only holds drop-free —
    exactly why decode uses per-call capacity from its own T."""
    import dataclasses
    cfg = dataclasses.replace(get_model_config("tiny-moe"),
                              moe_capacity_factor=4.0)
    model = Transformer(cfg)
    params = model.init(jax.random.key(1))
    rs = np.random.RandomState(6)
    b, t = 2, 8
    ids = jnp.asarray(rs.randint(1, 100, (b, t)), jnp.int32)
    mask = jnp.ones((b, t), jnp.int32)
    full = model.apply(params, ids, attention_mask=mask)

    logits0, cache = model.start_decode(params, ids[:, :4],
                                        jnp.ones((b, 4), jnp.int32), t - 4)
    np.testing.assert_allclose(np.asarray(logits0),
                               np.asarray(full[:, 3]), rtol=2e-4, atol=2e-4)
    logits = logits0
    for s in range(t - 4 - 1):
        logits, cache = model.decode_step(params, cache, ids[:, 4 + s])
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[:, 4 + s]),
            rtol=2e-4, atol=2e-4)


def test_moe_pads_never_claim_capacity():
    """Padding tokens must not evict real tokens from expert slots or
    enter the router statistics: a row of real tokens routes identically
    whether or not pads share the batch row."""
    rs = np.random.RandomState(7)
    d, f, e = 6, 10, 4
    rw, wg, wu, wd = _moe_weights(seed=8, d=d, f=f, e=e)
    real = rs.randn(1, 8, d).astype(np.float32)
    # tight capacity so eviction WOULD happen if pads took slots
    out_alone, aux_alone = moe_mlp(
        jnp.asarray(real), rw, wg, wu, wd, k=2, capacity_factor=1.0)
    padded = np.concatenate([real, np.tile(real[:, :1], (1, 8, 1))], axis=1)
    valid = jnp.asarray(np.concatenate(
        [np.ones((1, 8), np.int32), np.zeros((1, 8), np.int32)], axis=1))
    # group_size=8 makes the real tokens their own group with the SAME
    # per-group capacity as the alone run; the pad group claims nothing
    out_padded, aux_padded = moe_mlp(
        jnp.asarray(padded), rw, wg, wu, wd, k=2, capacity_factor=1.0,
        valid=valid, group_size=8)
    np.testing.assert_allclose(np.asarray(out_padded)[:, :8],
                               np.asarray(out_alone), rtol=1e-4, atol=1e-5)
    # stats computed over real tokens only
    np.testing.assert_allclose(float(aux_padded.load_balance),
                               float(aux_alone.load_balance), rtol=1e-5)


def test_moe_grouping_is_o_t():
    """Token grouping bounds the dispatch tensor: per-group capacity at
    T=64/group=16 equals the T=16 capacity, and parity holds with the
    ungrouped computation when nothing drops."""
    rs = np.random.RandomState(9)
    h = jnp.asarray(rs.randn(1, 64, 6).astype(np.float32))
    rw, wg, wu, wd = _moe_weights(seed=10)
    grouped, _ = moe_mlp(h, rw, wg, wu, wd, k=2, capacity_factor=50.0,
                         group_size=16)
    ungrouped, _ = moe_mlp(h, rw, wg, wu, wd, k=2, capacity_factor=50.0,
                           group_size=64)
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(ungrouped),
                               rtol=1e-4, atol=1e-5)


def test_moe_config_guards():
    with pytest.raises(ValueError, match="llama block only"):
        ModelConfig(vocab_size=8, hidden_size=8, intermediate_size=8,
                    num_layers=1, num_heads=1, num_kv_heads=1,
                    arch="phi", num_experts=2)
    with pytest.raises(ValueError, match="attention projections"):
        ModelConfig(vocab_size=8, hidden_size=8, intermediate_size=8,
                    num_layers=1, num_heads=1, num_kv_heads=1,
                    num_experts=2, lora_r=4,
                    lora_targets=("wq", "w_gate"))

def test_moe_pipeline_parity_and_aux():
    """MoE x PP (round-5 verdict item 4): the router's aux scalars ride
    the stage schedule (masked tick sums, psum at collection). Hidden
    states match the plain forward; router_z / dropped_frac are linear
    in tokens so their microbatch means equal the full-batch stats
    exactly; load_balance is a product of per-expert means, so the
    per-microbatch convention (same as grad accumulation's) differs
    within a small tolerance."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = get_model_config("tiny-moe")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    rs = np.random.RandomState(40)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    mask = jnp.ones((4, 16), jnp.int32)
    want_h, want_aux = model.hidden_states_with_aux(params, ids, mask)
    mesh = build_mesh(MeshConfig(stage=2, data=1, fsdp=2, model=1,
                                 sequence=1, expert=2))
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        got_h, got_aux = jax.jit(
            lambda p: model.hidden_states_with_aux(p, ids, mask))(sp)
    np.testing.assert_allclose(np.asarray(got_h), np.asarray(want_h),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(float(got_aux.router_z),
                               float(want_aux.router_z), rtol=1e-5)
    np.testing.assert_allclose(float(got_aux.dropped_frac),
                               float(want_aux.dropped_frac), atol=1e-6)
    np.testing.assert_allclose(float(got_aux.load_balance),
                               float(want_aux.load_balance), rtol=5e-2)


def test_moe_pipeline_grads_flow_through_router():
    """Backward through MoE x PP: the balance loss trains the router via
    the masked-psum collection path (grads match the plain scan within
    the microbatch-statistics tolerance)."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = get_model_config("tiny-moe")
    model = Transformer(cfg)
    params = model.init(jax.random.key(1))
    rs = np.random.RandomState(41)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)
    batch = {"input_ids": ids,
             "attention_mask": np.ones((4, 16), np.int32),
             "labels": jnp.where(ids % 5 == 0, -100, ids)}

    def loss(p):
        return model_fused_ce(model, p, batch)[0]

    g_ref = jax.grad(loss)(params)
    mesh = build_mesh(MeshConfig(stage=2, data=1, fsdp=2, model=1,
                                 sequence=1, expert=2))
    with jax.sharding.set_mesh(mesh):
        sp = jax.device_put(params, sharding_tree(model.partition_specs(),
                                                  mesh))
        g_pp = jax.jit(jax.grad(loss))(sp)
    # the router grad must be nonzero (balance loss collected) and close
    router_ref = np.asarray(g_ref["layers"]["router"])
    router_pp = np.asarray(g_pp["layers"]["router"])
    assert np.abs(router_pp).max() > 0
    np.testing.assert_allclose(router_pp, router_ref, rtol=5e-2,
                               atol=5e-4)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=5e-4)
