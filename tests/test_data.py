"""Golden tests for data semantics: templating, prompt masking, padding,
preference pairs, packing, per-host sharded iteration."""
import json

import numpy as np
import pytest

from dla_tpu.data import (
    IGNORE_INDEX,
    ByteTokenizer,
    InstructionDataset,
    PackedInstructionDataset,
    PreferenceDataset,
    ShardedBatchIterator,
    TeacherRolloutDataset,
    encode_prompt_response,
    load_instruction_records,
    load_prompt_records,
    read_jsonl,
    write_jsonl,
)


@pytest.fixture
def tok():
    return ByteTokenizer()


def test_byte_tokenizer_roundtrip(tok):
    ids = tok.encode("hello, wörld", add_eos=True)
    assert ids[0] == tok.bos_token_id and ids[-1] == tok.eos_token_id
    assert tok.decode(ids) == "hello, wörld"


def test_template_and_prompt_mask(tok):
    ex = encode_prompt_response(tok, "  What is 2+2?  ", "4", 128)
    text = tok.decode(list(ex["input_ids"]))
    # reference template: "{prompt}\n\n{response}" with stripped fields
    assert text == "What is 2+2?\n\n4"
    assert ex["input_ids"][-1] == tok.eos_token_id
    prompt_len = len(tok.encode("What is 2+2?\n\n"))
    assert (ex["labels"][:prompt_len] == IGNORE_INDEX).all()
    assert (ex["labels"][prompt_len:] != IGNORE_INDEX).all()
    # labels equal ids where unmasked
    np.testing.assert_array_equal(
        ex["labels"][prompt_len:], ex["input_ids"][prompt_len:])


def test_truncation(tok):
    ex = encode_prompt_response(tok, "p" * 100, "r" * 100, 50)
    assert ex["input_ids"].shape[0] == 50


def test_instruction_dataset_collate_static_shape(tok):
    recs = [{"prompt": "a", "response": "bb"},
            {"prompt": "ccc", "response": "dddd"}]
    ds = InstructionDataset(tok, max_length=32, records=recs)
    batch = ds.collate([ds[0], ds[1]])
    assert batch["input_ids"].shape == (2, 32)
    assert batch["attention_mask"].shape == (2, 32)
    assert batch["labels"].shape == (2, 32)
    # pad region: ids = pad_id, mask = 0, labels = -100
    n0 = len(tok.encode("a\n\nbb", add_eos=True))
    assert (batch["input_ids"][0, n0:] == tok.pad_token_id).all()
    assert (batch["attention_mask"][0, n0:] == 0).all()
    assert (batch["labels"][0, n0:] == IGNORE_INDEX).all()


def test_preference_dataset_sides_independent(tok):
    recs = [{"prompt": "q", "chosen": "good answer", "rejected": "bad"}]
    ds = PreferenceDataset(tok, max_length=64, records=recs)
    batch = ds.collate([ds[0]])
    c = tok.decode(list(batch["chosen"]["input_ids"][0]))
    r = tok.decode(list(batch["rejected"]["input_ids"][0]))
    assert c == "q\n\ngood answer"
    assert r == "q\n\nbad"
    # prompt masked on both sides
    plen = len(tok.encode("q\n\n"))
    assert (batch["chosen"]["labels"][0, :plen] == IGNORE_INDEX).all()
    assert (batch["rejected"]["labels"][0, :plen] == IGNORE_INDEX).all()


def test_teacher_rollout_dataset(tok, tmp_path):
    p = tmp_path / "rollouts.jsonl"
    write_jsonl(p, [
        {"prompt": "q1", "teacher_response": "a1", "reward": 0.5},
        {"prompt": "q2", "teacher_response": "a2"},
    ])
    ds = TeacherRolloutDataset(tok, 32, path=str(p))
    batch = ds.collate([ds[0], ds[1]])
    # labels == input_ids on real tokens (no prompt mask)
    real = batch["attention_mask"].astype(bool)
    np.testing.assert_array_equal(
        batch["labels"][real], batch["input_ids"][real])
    np.testing.assert_allclose(batch["reward"], [0.5, 1.0])


def test_jsonl_roundtrip(tmp_path):
    p = tmp_path / "x.jsonl"
    recs = [{"a": 1}, {"b": "ü"}]
    write_jsonl(p, recs)
    assert read_jsonl(p) == recs
    # blank lines tolerated
    with open(p, "a") as fh:
        fh.write("\n\n")
    assert read_jsonl(p) == recs


def test_load_instruction_records_local_with_limit(tmp_path):
    p = tmp_path / "sft.jsonl"
    write_jsonl(p, [{"prompt": f"p{i}", "response": f"r{i}"} for i in range(10)])
    recs = load_instruction_records(
        {"source": "local", "train_path": str(p), "limit": 3}, "train")
    assert len(recs) == 3 and recs[0]["prompt"] == "p0"


def test_load_prompt_records_local(tmp_path):
    p = tmp_path / "prompts.jsonl"
    write_jsonl(p, [{"prompt": "hello"}, {"prompt": ""}, {"prompt": "world"}])
    prompts = load_prompt_records({"source": "local", "prompt_path": str(p)})
    assert prompts == ["hello", "world"]  # empties dropped


def test_packing_preserves_tokens_and_masks(tok):
    recs = [{"prompt": f"prompt {i}", "response": "resp " * (i + 1)}
            for i in range(6)]
    base = InstructionDataset(tok, max_length=64, records=recs)
    packed = PackedInstructionDataset(base, max_length=64)
    assert len(packed) < len(base)  # actually packed something
    assert packed.packing_efficiency() > 0.5
    total_real = sum(int(base[i]["attention_mask"].sum()) for i in range(len(base)))
    total_packed = sum(int(packed[i]["attention_mask"].sum())
                       for i in range(len(packed)))
    assert total_real == total_packed
    row = packed[0]
    # segment ids: 0 on padding, >=1 on real tokens; labels -100 on padding
    assert (row["segment_ids"][row["attention_mask"] == 0] == 0).all()
    assert (row["segment_ids"][row["attention_mask"] == 1] >= 1).all()
    assert (row["labels"][row["attention_mask"] == 0] == IGNORE_INDEX).all()


def test_sharded_iterator_partitions_globally(tok):
    recs = [{"prompt": f"p{i}", "response": f"r{i}"} for i in range(16)]
    ds = InstructionDataset(tok, max_length=16, records=recs)
    # two "hosts" must see disjoint halves of the same global batch
    it0 = ShardedBatchIterator(ds, 8, seed=1, process_index=0, process_count=2)
    it1 = ShardedBatchIterator(ds, 8, seed=1, process_index=1, process_count=2)
    b0 = next(iter(it0))
    b1 = next(iter(it1))
    assert b0["input_ids"].shape[0] == 4
    ids0 = {tuple(r) for r in b0["input_ids"]}
    ids1 = {tuple(r) for r in b1["input_ids"]}
    assert not (ids0 & ids1)


def test_sharded_iterator_resume(tok):
    recs = [{"prompt": f"p{i}", "response": f"r{i}"} for i in range(12)]
    ds = InstructionDataset(tok, max_length=16, records=recs)
    it = ShardedBatchIterator(ds, 4, seed=3)
    gen = iter(it)
    next(gen); next(gen)
    state = it.state_dict()
    want = next(gen)

    it2 = ShardedBatchIterator(ds, 4, seed=3)
    it2.load_state_dict(state)
    got = next(iter(it2))
    np.testing.assert_array_equal(want["input_ids"], got["input_ids"])


def test_sharded_iterator_epoch_reshuffle(tok):
    recs = [{"prompt": f"p{i}", "response": f"r{i}"} for i in range(8)]
    ds = InstructionDataset(tok, max_length=16, records=recs)
    it = ShardedBatchIterator(ds, 8, seed=5)
    gen = iter(it)
    e0 = next(gen)["input_ids"]
    e1 = next(gen)["input_ids"]  # next epoch (8/8 = 1 step per epoch)
    assert not np.array_equal(e0, e1)
    # same content, different order
    assert ({tuple(r) for r in e0} == {tuple(r) for r in e1})


# --------------------------------------------------- weighted mixtures


def _write_source(tmp_path, name, n, tag):
    from dla_tpu.data.jsonl import write_jsonl
    p = tmp_path / f"{name}.jsonl"
    write_jsonl(p, [{"prompt": f"{tag} q{i}", "response": f"{tag} a{i}"}
                    for i in range(n)])
    return str(p)


def test_mixture_apportions_by_weight(tmp_path):
    from dla_tpu.data.loaders import load_instruction_records

    a = _write_source(tmp_path, "a", 20, "A")
    b = _write_source(tmp_path, "b", 20, "B")
    cfg = {"mixture": [{"train_path": a, "weight": 3.0},
                       {"train_path": b, "weight": 1.0}],
           "mixture_size": 16}
    recs = load_instruction_records(cfg)
    assert len(recs) == 16
    n_a = sum(1 for r in recs if r["prompt"].startswith("A"))
    assert n_a == 12 and len(recs) - n_a == 4


def test_mixture_deterministic_and_oversampled(tmp_path):
    """A source smaller than its quota repeats deterministically; two
    loads produce identical epochs (multi-host coherence)."""
    from dla_tpu.data.loaders import load_instruction_records

    a = _write_source(tmp_path, "small", 3, "S")
    b = _write_source(tmp_path, "big", 30, "L")
    cfg = {"mixture": [{"train_path": a, "weight": 1.0},
                       {"train_path": b, "weight": 1.0}],
           "mixture_size": 20, "mixture_seed": 7}
    r1 = load_instruction_records(cfg)
    r2 = load_instruction_records(cfg)
    assert r1 == r2
    assert sum(1 for r in r1 if r["prompt"].startswith("S")) == 10
    # the 3-row source fills its 10-slot quota by repetition
    assert len({r["prompt"] for r in r1 if r["prompt"].startswith("S")}) == 3


def test_mixture_entries_inherit_outer_keys(tmp_path):
    from dla_tpu.data.loaders import load_instruction_records

    a = _write_source(tmp_path, "x", 10, "X")
    # outer limit applies per source unless the entry overrides it
    cfg = {"mixture": [{"train_path": a}], "limit": 4}
    assert len(load_instruction_records(cfg)) == 4


def test_mixture_preference_records(tmp_path):
    from dla_tpu.data.jsonl import write_jsonl
    from dla_tpu.data.loaders import load_preference_records

    p = tmp_path / "pref.jsonl"
    write_jsonl(p, [{"prompt": f"q{i}", "chosen": "good", "rejected": "bad"}
                    for i in range(6)])
    cfg = {"mixture": [{"train_path": str(p), "weight": 1.0}],
           "mixture_size": 6}
    recs = load_preference_records(cfg)
    assert len(recs) == 6 and recs[0]["chosen"] == "good"


def test_mixture_does_not_touch_eval_split(tmp_path):
    """The mixture shapes the training epoch only — eval loads the outer
    config's held-out set untouched (no weighting/oversampling)."""
    from dla_tpu.data.jsonl import write_jsonl
    from dla_tpu.data.loaders import load_instruction_records

    a = _write_source(tmp_path, "trn", 10, "T")
    ev = tmp_path / "eval.jsonl"
    write_jsonl(ev, [{"prompt": f"e{i}", "response": f"r{i}"}
                     for i in range(5)])
    cfg = {"mixture": [{"train_path": a, "weight": 2.0}],
           "mixture_size": 40, "eval_path": str(ev)}
    recs = load_instruction_records(cfg, split="eval")
    assert len(recs) == 5 and recs[0]["prompt"] == "e0"


def test_mixture_entry_source_not_inherited(tmp_path):
    """A local-path entry under an outer `source: hf` config must load
    its own JSONL, not the outer HF dataset."""
    from dla_tpu.data.loaders import load_instruction_records

    a = _write_source(tmp_path, "local_src", 6, "LOC")
    cfg = {"source": "hf", "hf_path": "would-hit-network/if-inherited",
           "mixture": [{"train_path": a, "weight": 1.0}],
           "mixture_size": 6}
    recs = load_instruction_records(cfg)
    assert len(recs) == 6
    assert all(r["prompt"].startswith("LOC") for r in recs)
