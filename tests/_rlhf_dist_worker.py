"""Worker for the FOUR-process RLHF smoke (test_multiprocess.py).

Each of 4 processes owns 2 virtual CPU devices; together they form one
8-device world. Covers what the 2-process SFT-side test cannot (r4
VERDICT item 8): the RLHF rollout loop's multi-host prompt sharding
(each host samples its local_bs = batch/process_count prompt slice and
contributes rollout rows to the global train batch) and the
``latest``-pointer phase chaining (SFT writes checkpoints, RLHF loads
the policy from the SFT output dir through `latest`).

Usage: python tests/_rlhf_dist_worker.py <port> <rank> <workdir>
(launched with a scrubbed CPU env forcing 2 host-platform devices).
"""
import json
import sys
from pathlib import Path


def main() -> None:
    port, rank, workdir = sys.argv[1], int(sys.argv[2]), Path(sys.argv[3])
    import jax
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=4,
        process_id=rank)
    assert jax.process_count() == 4, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 2, jax.local_device_count()

    import numpy as np
    import yaml

    from dla_tpu.data.jsonl import write_jsonl
    from dla_tpu.parallel.dist import barrier

    # every process writes identical inputs into ITS OWN view of the
    # shared tmpdir exactly once (rank 0), others wait
    sft_data = workdir / "sft_train.jsonl"
    prompts = workdir / "prompts.jsonl"
    if rank == 0:
        rng = np.random.default_rng(0)
        write_jsonl(sft_data, [
            {"prompt": f"add {int(rng.integers(0, 9))}",
             "response": str(int(rng.integers(0, 9)))} for _ in range(64)])
        write_jsonl(prompts, [{"prompt": f"say {i}"} for i in range(32)])
    barrier("inputs-ready")

    mesh = {"data": 2, "fsdp": 2, "model": 2, "sequence": 1}

    # ---- phase 1: SFT writes the checkpoint chain -------------------
    sft_out = workdir / "sft_ckpt"
    sft_cfg = {
        "experiment_name": "dist_sft", "seed": 0,
        "model": {"model_name_or_path": "tiny", "tokenizer": "byte",
                  "max_seq_length": 16},
        "data": {"source": "local", "train_path": str(sft_data)},
        "optimization": {"total_batch_size": 8, "micro_batch_size": 2,
                         "learning_rate": 1e-3, "warmup_steps": 1,
                         "max_train_steps": 2, "lr_scheduler": "constant",
                         "max_grad_norm": 1.0},
        "logging": {"output_dir": str(sft_out),
                    "log_dir": str(workdir / "sft_logs"),
                    "log_every_steps": 1, "save_every_steps": 2},
        "hardware": {"gradient_accumulation_steps": 1, "mesh": mesh},
    }
    p = workdir / f"sft_{rank}.yaml"
    p.write_text(yaml.safe_dump(sft_cfg))
    from dla_tpu.training.train_sft import main as sft_main
    sft_main(["--config", str(p)])
    barrier("sft-done")
    assert (sft_out / "latest").is_file(), "SFT latest pointer missing"

    # ---- phase 2: RLHF loads the policy via the latest pointer ------
    rlhf_cfg = {
        "experiment_name": "dist_rlhf", "seed": 0,
        "model": {
            # phase chaining: resolves sft_ckpt/latest -> step dir
            "policy_model_name_or_path": str(sft_out),
            "reference_model_name_or_path": str(sft_out),
            "tokenizer": "byte", "max_seq_length": 24,
        },
        "reward_model": {"base_model_name_or_path": "tiny",
                         "tokenizer": "byte", "max_seq_length": 24},
        "ppo": {
            "algo": "reinforce", "batch_size": 8, "learning_rate": 1e-4,
            "kl_coef": 0.1, "steps": 2,
            "generation_params": {"max_new_tokens": 4,
                                  "temperature": 0.7, "top_p": 0.9},
        },
        "sampling": {"source": "local", "prompt_path": str(prompts)},
        "logging": {"output_dir": str(workdir / "rlhf_ckpt"),
                    "log_dir": str(workdir / "rlhf_logs"),
                    "log_every_steps": 1},
        "hardware": {"mesh": mesh},
    }
    p = workdir / f"rlhf_{rank}.yaml"
    p.write_text(yaml.safe_dump(rlhf_cfg))
    from dla_tpu.training.train_rlhf import main as rlhf_main
    rlhf_main(["--config", str(p)])
    barrier("rlhf-done")

    if rank == 0:
        recs = [json.loads(l)
                for l in open(workdir / "rlhf_logs" / "metrics.jsonl")]
        steps = [r for r in recs if "train/reward_mean" in r]
        assert len(steps) >= 2, f"expected >=2 RLHF steps logged: {recs}"
        for r in steps:
            assert np.isfinite(r["train/reward_mean"]), r
    print(f"[rlhf-worker {rank}] OK", flush=True)


if __name__ == "__main__":
    main()
