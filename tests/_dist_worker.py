"""Worker process for the two-process jax.distributed test
(test_multiprocess.py). Each of 2 processes owns 4 virtual CPU devices;
together they form one 8-device world exercising the code paths that are
no-ops at process_count() == 1: make_array_from_process_local_data
batch assembly, local_numpy's multi-host branch, the cross-host barrier,
and per-host checkpoint shard writes.

Usage: python tests/_dist_worker.py <coordinator_port> <rank> <ckpt_dir>
(launched with a scrubbed CPU env; XLA_FLAGS must already force 4
host-platform devices).
"""
import sys

import numpy as np


def main() -> None:
    port, rank, outdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    import jax
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2,
        process_id=rank)
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dla_tpu.checkpoint.checkpointer import Checkpointer
    from dla_tpu.parallel.dist import barrier
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.parallel.sharding import local_numpy, make_global_batch

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4, jax.local_device_count()
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, model=2, sequence=1))

    # --- global batch assembly: each host contributes 8 of 16 rows
    local = (np.arange(rank * 8, rank * 8 + 8, dtype=np.int32)[:, None]
             * np.ones((1, 4), np.int32))
    with jax.sharding.set_mesh(mesh):
        g = make_global_batch({"x": local}, mesh)["x"]
        assert g.shape == (16, 4), g.shape
        # SPMD reduction over the 2-process world: mean of row values 0..15
        mean = float(jax.jit(lambda a: jnp.mean(a.astype(jnp.float32)))(g))
        assert abs(mean - 7.5) < 1e-6, mean
        # local_numpy multi-host branch: this host's slice, in order
        back = local_numpy(g)
        assert np.array_equal(back, local), (back.tolist(), rank)

        # --- per-host checkpoint shard writes (deterministic content so
        # the parent can verify a cross-topology restore value-for-value)
        full = np.arange(16 * 12, dtype=np.float32).reshape(16, 12)
        tree = {
            "w": jax.device_put(jnp.asarray(full),
                                NamedSharding(mesh, P("fsdp", "model"))),
            "b": jax.device_put(jnp.arange(12, dtype=np.float32),
                                NamedSharding(mesh, P())),
        }
        ck = Checkpointer(outdir, keep_last_n=2)
        ck.save(7, tree, aux={"who": "dist_worker"})
    barrier("workers_done")
    print(f"[worker {rank}] OK", flush=True)


if __name__ == "__main__":
    main()
