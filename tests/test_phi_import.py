"""Phi-family support: logits parity with transformers' PhiForCausalLM
(parallel residual block, partial rotary, LayerNorm, biased projections)
on a tiny randomly-initialized model saved to disk — the real phi-2
architecture the reference uses as its distillation student
(reference config/distill_config.yaml model block)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_phi_dir(tmp_path_factory):
    from transformers import PhiConfig, PhiForCausalLM
    cfg = PhiConfig(
        vocab_size=160, hidden_size=40, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, partial_rotary_factor=0.4,
        layer_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = PhiForCausalLM(cfg).eval()
    d = tmp_path_factory.mktemp("hf_phi")
    model.save_pretrained(str(d), safe_serialization=True)
    return d, model


def test_phi_config_mapping(tiny_phi_dir):
    d, _ = tiny_phi_dir
    from dla_tpu.models.hf_import import hf_config_to_model_config, read_hf_config
    cfg = hf_config_to_model_config(read_hf_config(d))
    assert cfg.arch == "phi"
    assert cfg.rotary_pct == 0.4
    assert cfg.rotary_dim_ == 4  # head_dim 10 * 0.4 = 4
    assert cfg.num_layers == 2


def test_phi_import_matches_hf_logits(tiny_phi_dir):
    d, hf_model = tiny_phi_dir
    import jax.numpy as jnp
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer

    cfg = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none")
    params = import_hf_weights(d, cfg)
    model = Transformer(cfg)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 160, (2, 12))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_phi_decode_matches_full_forward(tiny_phi_dir):
    """KV-cache decode path (prefill + step) must agree with the full
    re-forward for the phi block too."""
    d, _ = tiny_phi_dir
    import jax
    import jax.numpy as jnp
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer

    cfg = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none")
    params = jax.tree.map(jnp.asarray, import_hf_weights(d, cfg))
    model = Transformer(cfg)

    rs = np.random.RandomState(1)
    b, t, new = 2, 6, 3
    ids = jnp.asarray(rs.randint(0, 160, (b, t)), jnp.int32)
    mask = jnp.ones((b, t), jnp.int32)

    logits, cache = model.start_decode(params, ids, mask, max_new_tokens=new)
    seq = ids
    for step in range(new):
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        logits, cache = model.decode_step(params, cache, nxt)
        full = model.apply(params, seq,
                           attention_mask=jnp.ones_like(seq))[:, -1]
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full), rtol=2e-3, atol=2e-4)


def test_phi_preset_trains(tmp_path):
    """The registry phi-2 preset (scaled tiny here) takes a full sharded
    train step — parallel block + biases flow through grads."""
    import jax
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.losses import cross_entropy_loss
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.training.trainer import Trainer

    cfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=4, arch="phi", rotary_pct=0.4,
        dtype="float32", param_dtype="float32", remat="none")
    model = Transformer(cfg)
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, model=2, sequence=1),
                      devices=jax.devices()[:8])

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        logits = model.apply(p, batch["input_ids"],
                             attention_mask=batch["attention_mask"])
        loss, _ = cross_entropy_loss(logits, batch["labels"])
        return loss, {}

    config = {
        "experiment_name": "phi_step",
        "optimization": {"total_batch_size": 8, "micro_batch_size": 2,
                         "learning_rate": 1e-3, "max_train_steps": 3,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": str(tmp_path), "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    rs = np.random.RandomState(0)
    batch = {"input_ids": rs.randint(1, 128, (8, 16)).astype(np.int32),
             "attention_mask": np.ones((8, 16), np.int32),
             "labels": rs.randint(1, 128, (8, 16)).astype(np.int32)}
    with jax.sharding.set_mesh(mesh):
        trainer = Trainer(config=config, mesh=mesh, loss_fn=loss_fn,
                          params=model.init(jax.random.key(0)),
                          param_specs=model.partition_specs())
        losses = [trainer.step_on_batch(batch, jax.random.key(i))[0]
                  for i in range(3)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
