"""Pallas decode-attention kernel + fused int8 matmul: parity with the
XLA paths they replace (CPU interpret mode; the same code runs compiled
on TPU, where sweep_decode measures the byte-traffic win)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dla_tpu.models.config import ModelConfig
from dla_tpu.models.transformer import Transformer
from dla_tpu.ops.attention import decode_attention
from dla_tpu.ops.decode_kernel import flash_decode_attention
from dla_tpu.ops.quant_matmul import int8_matmul

RNG = np.random.RandomState(0)


def _sym_int8(x, axis):
    absm = jnp.max(jnp.abs(x.astype(jnp.float32)), axis)
    sc = absm / 127.0 + 1e-12
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / sc[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, sc


@pytest.mark.parametrize("b,s,h,kh,win", [
    (2, 256, 8, 4, None),    # GQA, block-exact S
    (1, 140, 4, 2, 32),      # ragged S + sliding window
    (2, 128, 16, 2, None),   # MHA-ish wide group (g=8 == GP)
    (1, 260, 8, 8, None),    # MHA, ragged
])
def test_decode_kernel_matches_xla_bf16(b, s, h, kh, win):
    d = 128
    q = jnp.asarray(RNG.randn(b, 1, h, d), jnp.bfloat16)
    kc = jnp.asarray(RNG.randn(b, s, kh, d), jnp.bfloat16)
    vc = jnp.asarray(RNG.randn(b, s, kh, d), jnp.bfloat16)
    kn = jnp.asarray(RNG.randn(b, 1, kh, d), jnp.bfloat16)
    vn = jnp.asarray(RNG.randn(b, 1, kh, d), jnp.bfloat16)
    valid = jnp.asarray(RNG.rand(b, s) < 0.7)
    qpos = jnp.full((b, 1), s // 2, jnp.int32)
    kpos = jnp.broadcast_to(jnp.arange(s)[None], (b, s)).astype(jnp.int32)
    kw = dict(kv_valid=valid, q_positions=qpos, kv_positions=kpos,
              window=win)
    ref = decode_attention(q, kc, vc, kn, vn, **kw)
    out = flash_decode_attention(q, kc, vc, kn, vn, **kw)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=8e-3)


def test_decode_kernel_int8_dequant_in_kernel():
    """int8 cache + scales through the kernel == dequantize-then-XLA."""
    b, s, h, kh, d = 2, 200, 8, 4, 128
    q = jnp.asarray(RNG.randn(b, 1, h, d), jnp.bfloat16)
    kc = jnp.asarray(RNG.randn(b, s, kh, d), jnp.bfloat16)
    vc = jnp.asarray(RNG.randn(b, s, kh, d), jnp.bfloat16)
    kn = jnp.asarray(RNG.randn(b, 1, kh, d), jnp.bfloat16)
    vn = jnp.asarray(RNG.randn(b, 1, kh, d), jnp.bfloat16)
    valid = jnp.asarray(RNG.rand(b, s) < 0.8)
    qpos = jnp.full((b, 1), s // 2, jnp.int32)
    kpos = jnp.broadcast_to(jnp.arange(s)[None], (b, s)).astype(jnp.int32)
    kq, ksc = _sym_int8(kc, -1)
    vq, vsc = _sym_int8(vc, -1)
    kd = (kq.astype(jnp.float32) * ksc[..., None]).astype(jnp.bfloat16)
    vd = (vq.astype(jnp.float32) * vsc[..., None]).astype(jnp.bfloat16)
    kw = dict(kv_valid=valid, q_positions=qpos, kv_positions=kpos)
    ref = decode_attention(q, kd, vd, kn, vn, **kw)
    # scales are K-major [B, K, S] (the decode cache's storage layout)
    out = flash_decode_attention(q, kq, vq, kn, vn,
                                 k_scale=ksc.transpose(0, 2, 1),
                                 v_scale=vsc.transpose(0, 2, 1), **kw)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=8e-3)


def test_decode_kernel_fully_masked_cache_row():
    """A row whose cache is entirely invalid attends only to itself."""
    b, s, h, kh, d = 1, 128, 4, 2, 128
    q = jnp.asarray(RNG.randn(b, 1, h, d), jnp.bfloat16)
    kc = jnp.asarray(RNG.randn(b, s, kh, d), jnp.bfloat16)
    vc = jnp.asarray(RNG.randn(b, s, kh, d), jnp.bfloat16)
    kn = jnp.asarray(RNG.randn(b, 1, kh, d), jnp.bfloat16)
    vn = jnp.asarray(RNG.randn(b, 1, kh, d), jnp.bfloat16)
    valid = jnp.zeros((b, s), bool)
    qpos = jnp.zeros((b, 1), jnp.int32)
    kpos = jnp.broadcast_to(jnp.arange(s)[None], (b, s)).astype(jnp.int32)
    out = flash_decode_attention(q, kc, vc, kn, vn, kv_valid=valid,
                                 q_positions=qpos, kv_positions=kpos)
    want = jnp.broadcast_to(vn.reshape(b, 1, kh, 1, d),
                            (b, 1, kh, h // kh, d)).reshape(b, 1, h, d)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(want, np.float32), atol=1e-2)


def _hd128_cfg(**over):
    return ModelConfig(
        vocab_size=256, hidden_size=256, intermediate_size=512,
        num_layers=2, num_heads=2, num_kv_heads=1, max_seq_length=128,
        attention="xla", remat="none", dtype="bfloat16",
        param_dtype="bfloat16", rope_theta=10000.0, **over)


def test_decode_step_int8_cache_uses_kernel_and_matches():
    """End-to-end decode_step with an int8 cache: the kernel path (gate
    on: head_dim 128) matches the XLA dequant path bit-for-tolerance."""
    cfg = _hd128_cfg(kv_cache_dtype="int8")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    b, t, n = 2, 16, 4
    ids = jnp.asarray(RNG.randint(3, 250, (b, t)), jnp.int32)
    mask = jnp.ones((b, t), jnp.int32)
    mask = mask.at[1, t - 3:].set(0)  # one padded row

    logits, cache = model.start_decode(params, ids, mask, n)
    tok = jnp.argmax(logits, -1).astype(jnp.int32)

    assert cfg.head_dim_ == 128  # the kernel gate is open on this config
    l_kernel, cache_k = model.decode_step(params, cache, tok)

    # force the XLA path by monkeypatching flash_decode_attention to the
    # dequantize-then-decode_attention reference (decode_step re-imports
    # per trace, and these eager calls re-trace every time)
    from dla_tpu.ops import decode_kernel as dk

    def xla_ref(q, kc, vc, kn, vn, *, bias=None, kv_valid=None,
                q_positions=None, kv_positions=None,
                k_scale=None, v_scale=None, softmax_scale=None,
                window=None, **_):
        # K-major [B, K, S] scales -> positional; the precomputed bias
        # already folds validity+causality, so hand decode_attention a
        # pure-validity mask with always-causal positions
        b, s = kc.shape[0], kc.shape[1]
        kd = (kc.astype(jnp.float32)
              * k_scale.transpose(0, 2, 1)[..., None]).astype(jnp.bfloat16)
        vd = (vc.astype(jnp.float32)
              * v_scale.transpose(0, 2, 1)[..., None]).astype(jnp.bfloat16)
        valid = bias > -1.0
        return decode_attention(
            q, kd, vd, kn, vn, kv_valid=valid,
            q_positions=jnp.full((b, 1), 1 << 29, jnp.int32),
            kv_positions=jnp.zeros((b, s), jnp.int32),
            softmax_scale=softmax_scale, window=None)

    real = dk.flash_decode_attention
    dk.flash_decode_attention = xla_ref
    try:
        l_xla, cache_x = model.decode_step(params, cache, tok)
    finally:
        dk.flash_decode_attention = real
    np.testing.assert_allclose(np.asarray(l_kernel, np.float32),
                               np.asarray(l_xla, np.float32),
                               atol=0.05, rtol=0.05)
    np.testing.assert_array_equal(np.asarray(cache_k["valid"]),
                                  np.asarray(cache_x["valid"]))


def test_decode_kernel_kv_fill_skips_tail_blocks():
    """With kv_fill set, cache content BEYOND the fill level must be
    unread: plant NaN there and require identical output to a zeroed
    tail. S=390 at block_s=128 spans 4 ragged blocks; fill=150 keeps
    blocks 0-1 active and clamps blocks 2-3 away."""
    b, s, h, kh, d = 2, 390, 8, 4, 128   # bs=128 -> 4 ragged blocks
    fill = 150
    q = jnp.asarray(RNG.randn(b, 1, h, d), jnp.bfloat16)
    kc = jnp.asarray(RNG.randn(b, s, kh, d), jnp.bfloat16)
    vc = jnp.asarray(RNG.randn(b, s, kh, d), jnp.bfloat16)
    kn = jnp.asarray(RNG.randn(b, 1, kh, d), jnp.bfloat16)
    vn = jnp.asarray(RNG.randn(b, 1, kh, d), jnp.bfloat16)
    valid = jnp.asarray(RNG.rand(b, s) < 0.8) & (
        jnp.arange(s)[None, :] < fill)
    qpos = jnp.full((b, 1), s, jnp.int32)
    kpos = jnp.broadcast_to(jnp.arange(s)[None], (b, s)).astype(jnp.int32)
    kw = dict(kv_valid=valid, q_positions=qpos, kv_positions=kpos,
              kv_fill=jnp.asarray(fill, jnp.int32), block_s=128)
    poison = jnp.where(jnp.arange(s)[None, :, None, None] >= fill,
                       jnp.nan, 0.0).astype(jnp.bfloat16)
    out_clean = flash_decode_attention(q, kc, vc, kn, vn, **kw)
    out_poison = flash_decode_attention(q, kc + poison, vc + poison,
                                        kn, vn, **kw)
    assert np.isfinite(np.asarray(out_poison, np.float32)).all()
    np.testing.assert_array_equal(np.asarray(out_clean),
                                  np.asarray(out_poison))
    # and the bounded result equals the unbounded one
    out_full = flash_decode_attention(q, kc, vc, kn, vn,
                                      **{**kw, "kv_fill": None})
    np.testing.assert_allclose(np.asarray(out_clean, np.float32),
                               np.asarray(out_full, np.float32),
                               atol=2e-3)


def test_decode_kernel_softcap_matches_xla():
    """Static logit softcapping (gemma-2) inside the kernel == the XLA
    decode_attention softcap path."""
    b, s, h, kh, d = 2, 200, 8, 4, 128
    q = jnp.asarray(RNG.randn(b, 1, h, d), jnp.bfloat16)
    kc = jnp.asarray(RNG.randn(b, s, kh, d), jnp.bfloat16)
    vc = jnp.asarray(RNG.randn(b, s, kh, d), jnp.bfloat16)
    kn = jnp.asarray(RNG.randn(b, 1, kh, d), jnp.bfloat16)
    vn = jnp.asarray(RNG.randn(b, 1, kh, d), jnp.bfloat16)
    valid = jnp.asarray(RNG.rand(b, s) < 0.8)
    qpos = jnp.full((b, 1), s // 2, jnp.int32)
    kpos = jnp.broadcast_to(jnp.arange(s)[None], (b, s)).astype(jnp.int32)
    kw = dict(kv_valid=valid, q_positions=qpos, kv_positions=kpos)
    ref = decode_attention(q, kc, vc, kn, vn, logit_softcap=50.0, **kw)
    out = flash_decode_attention(q, kc, vc, kn, vn, logit_softcap=50.0,
                                 **kw)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=8e-3)


def test_decode_step_gemma2_style_kernel_matches_xla():
    """gemma-2 composition — int8 cache + softcap + ALTERNATING per-layer
    windows (traced swa_on select between the two hoisted biases) —
    through the kernel matches the XLA dequant fallback."""
    cfg = _hd128_cfg(kv_cache_dtype="int8", sliding_window=8,
                     sliding_window_pattern=2, attn_logit_softcap=30.0)
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    b, t, n = 2, 12, 3
    ids = jnp.asarray(RNG.randint(3, 250, (b, t)), jnp.int32)
    mask = jnp.ones((b, t), jnp.int32)
    mask = mask.at[1, t - 4:].set(0)
    logits, cache = model.start_decode(params, ids, mask, n)
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    l_kernel, _ = model.decode_step(params, cache, tok)

    from dla_tpu.ops import decode_kernel as dk

    def xla_ref(q, kc, vc, kn, vn, *, bias=None, k_scale=None,
                v_scale=None, softmax_scale=None, logit_softcap=0.0, **_):
        b2, s2 = kc.shape[0], kc.shape[1]
        kd = (kc.astype(jnp.float32)
              * k_scale.transpose(0, 2, 1)[..., None]).astype(jnp.bfloat16)
        vd = (vc.astype(jnp.float32)
              * v_scale.transpose(0, 2, 1)[..., None]).astype(jnp.bfloat16)
        return decode_attention(
            q, kd, vd, kn, vn, kv_valid=bias > -1.0,
            q_positions=jnp.full((b2, 1), 1 << 29, jnp.int32),
            kv_positions=jnp.zeros((b2, s2), jnp.int32),
            softmax_scale=softmax_scale, logit_softcap=logit_softcap)

    real = dk.flash_decode_attention
    dk.flash_decode_attention = xla_ref
    try:
        l_xla, _ = model.decode_step(params, cache, tok)
    finally:
        dk.flash_decode_attention = real
    np.testing.assert_allclose(np.asarray(l_kernel, np.float32),
                               np.asarray(l_xla, np.float32),
                               atol=0.05, rtol=0.05)


# ---------------------------------------------------------------- int8 mm

@pytest.mark.parametrize("m,k,n", [(8, 256, 384), (3, 512, 256),
                                   (130, 256, 640)])
def test_int8_matmul_matches_dequant_matmul(m, k, n):
    w = jnp.asarray(RNG.randn(k, n) * 0.02, jnp.float32)
    q, sc = _sym_int8(w.T, -1)  # per-out-channel scales
    q, sc = q.T, sc[None, :]
    x = jnp.asarray(RNG.randn(m, k) * 0.5, jnp.float32)
    ref = (x.astype(jnp.bfloat16)
           @ (q.astype(jnp.float32) * sc).astype(jnp.bfloat16))
    out = int8_matmul(x, q, sc)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=0.05, rtol=0.05)


def test_int8_matmul_leading_dims_and_1d_scale():
    w = jnp.asarray(RNG.randn(128, 256) * 0.02, jnp.float32)
    q, sc = _sym_int8(w.T, -1)
    q = q.T
    x = jnp.asarray(RNG.randn(2, 3, 128), jnp.bfloat16)
    out = int8_matmul(x, q, sc)     # [N] scale, [B, T, K] input
    assert out.shape == (2, 3, 256)
    ref = int8_matmul(x.reshape(6, 128), q, sc[None, :]).reshape(2, 3, 256)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_decode_kernel_on_bf16_cache_matches_off():
    """decode_kernel: \"on\" routes a bf16 cache through the kernel;
    results must match decode_kernel: \"off\" (the XLA path) on the
    same params — multi-step, with a padded row."""
    import dataclasses as dc
    cfg_on = _hd128_cfg(decode_kernel="on")
    cfg_off = dc.replace(cfg_on, decode_kernel="off")
    m_on, m_off = Transformer(cfg_on), Transformer(cfg_off)
    params = m_on.init(jax.random.key(2))
    b, t, n = 2, 10, 3
    ids = jnp.asarray(RNG.randint(3, 250, (b, t)), jnp.int32)
    mask = jnp.ones((b, t), jnp.int32)
    mask = mask.at[0, t - 2:].set(0)
    l_on, c_on = m_on.start_decode(params, ids, mask, n)
    l_off, c_off = m_off.start_decode(params, ids, mask, n)
    np.testing.assert_array_equal(np.asarray(l_on), np.asarray(l_off))
    tok = jnp.argmax(l_on, -1).astype(jnp.int32)

    # spy on the kernel so a silently-closed gate cannot make this test
    # vacuously compare XLA against XLA
    from dla_tpu.ops import decode_kernel as dk
    calls = []
    real = dk.flash_decode_attention

    def spy(*a, **k):
        calls.append(1)
        return real(*a, **k)

    dk.flash_decode_attention = spy
    try:
        for _ in range(n):
            l_on, c_on = m_on.decode_step(params, c_on, tok)
            l_off, c_off = m_off.decode_step(params, c_off, tok)
            np.testing.assert_allclose(np.asarray(l_on, np.float32),
                                       np.asarray(l_off, np.float32),
                                       atol=0.05, rtol=0.05)
            tok = jnp.argmax(l_on, -1).astype(jnp.int32)
    finally:
        dk.flash_decode_attention = real
    assert calls, "decode_kernel='on' never reached the Pallas kernel"


def test_int8_matmul_blocks_shrink_to_fit_vmem():
    """Big-K shapes (7B/70B intermediate sizes) must auto-shrink the N
    block instead of overflowing VMEM — `_dense` cannot pass block
    overrides (r5 review finding)."""
    from dla_tpu.ops.quant_matmul import (
        _VMEM_BUDGET,
        DEFAULT_BLOCK_M,
        DEFAULT_BLOCK_N,
        _pick_blocks,
    )
    for m, k, n in [(256, 11008, 4096), (64, 28672, 8192),
                    (8192, 2816, 1024), (64, 1024, 32000)]:
        bm, bn = _pick_blocks(m, k, n, DEFAULT_BLOCK_M, DEFAULT_BLOCK_N)
        assert bm * k * 2 + 2 * k * bn + 2 * bm * bn * 2 <= _VMEM_BUDGET
        assert bn >= 128 and bm >= 16
    # moderate shapes keep the shipped default N tile (no needless grid
    # fragmentation)...
    assert _pick_blocks(64, 2816, 2816, DEFAULT_BLOCK_M, DEFAULT_BLOCK_N
                        ) == (64, DEFAULT_BLOCK_N)
    # ...and small-N projections clamp the tile to the (lane-aligned)
    # array instead of buffering phantom columns
    assert _pick_blocks(64, 1024, 256, DEFAULT_BLOCK_M, DEFAULT_BLOCK_N
                        ) == (64, 256)
    assert _pick_blocks(8, 1024, 100, DEFAULT_BLOCK_M, DEFAULT_BLOCK_N
                        ) == (16, 128)


def test_quantized_tree_decode_matches_fp_within_tolerance():
    """decode through a quantize_weights tree (kernel consumption) stays
    close to the full-precision decode — the same bar the pre-kernel
    XLA consumption path passed (test_generation.py)."""
    cfg = _hd128_cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.key(1))
    qparams = model.quantize_weights(params)
    assert qparams["layers"]["wq"].dtype == jnp.int8
    b, t = 2, 12
    ids = jnp.asarray(RNG.randint(3, 250, (b, t)), jnp.int32)
    mask = jnp.ones((b, t), jnp.int32)
    lf, _ = model.start_decode(params, ids, mask, 2)
    lq, _ = model.start_decode(qparams, ids, mask, 2)
    pf = jax.nn.softmax(lf.astype(jnp.float32), -1)
    pq = jax.nn.softmax(lq.astype(jnp.float32), -1)
    assert float(jnp.abs(pf - pq).max()) < 0.08
