"""KV page migration tests: a mid-decode request's committed pages
export as a ``MigrationTicket`` (one jitted gather), install on another
engine (one jitted scatter — compile counters pinned at 1 across every
further migration), and the request resumes bit-identically — greedy
AND explicitly-seeded sampled, COW-shared and cache-indexed pages
included, with correct refcounts and zero page leaks on both sides.
Exports refuse eviction holes (not-mid-decode, block-table drift) and
count them; a disaggregated 1-prefill + 2-decode fleet reproduces the
single engine's tokens exactly, including while the prefill member is
under chaos (handoffs are exactly-once: the journal entry moves between
supervisors atomically with the install)."""
import jax
import numpy as np
import pytest

from dla_tpu.serving import (
    TERMINAL_STATES,
    FleetConfig,
    FleetRouter,
    KVMigrator,
    MigrationConfig,
    MigrationError,
    SamplingParams,
    ServingConfig,
    ServingEngine,
    SupervisorConfig,
)

MAX_NEW = 6
PAGE = 4


@pytest.fixture(scope="module")
def serve_setup():
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    return model, params, gen


def _engine(serve_setup, **cfg_kw):
    """One engine with the migration-test geometry; fault_plan="" (not
    None) pins it fault-free even when $DLA_FAULT_PLAN is set."""
    model, params, gen = serve_setup
    kw = dict(page_size=PAGE, num_pages=64, num_slots=2,
              max_model_len=32, max_prefill_batch=2, prefill_chunk=PAGE,
              prefix_cache=True, fault_plan="")
    kw.update(cfg_kw)
    return ServingEngine(model, params, gen, ServingConfig(**kw))


def _run_to(eng, rid, n_generated):
    """Step until the request has streamed >= n_generated tokens —
    parked mid-decode, the only state a migration can export."""
    for _ in range(500):
        if len(eng.result(rid).generated) >= n_generated:
            return
        eng.step()
    raise AssertionError(f"request {rid} never reached "
                         f"{n_generated} generated tokens")


def _drain(eng):
    while eng.has_work():
        eng.step()


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------

def test_migration_config_validation():
    assert MigrationConfig.from_config(None).transport == "auto"
    assert MigrationConfig.from_config(
        {"enabled": True, "transport": "host"}).transport == "host"
    with pytest.raises(ValueError, match="transport"):
        MigrationConfig(transport="pigeon")
    with pytest.raises(ValueError, match="unknown migration"):
        MigrationConfig.from_config({"transports": "auto"})


def test_fleet_role_config_validation():
    cfg = FleetConfig(engines=3, roles=("prefill", "decode", "mixed"))
    assert cfg.role_for(0) == "prefill" and cfg.role_for(7) == "mixed"
    with pytest.raises(ValueError, match="every startup member"):
        FleetConfig(engines=3, roles=("prefill", "decode"))
    with pytest.raises(ValueError, match="drawn from"):
        FleetConfig(engines=2, roles=("prefill", "verifier"))
    with pytest.raises(ValueError, match="decode-capable"):
        FleetConfig(engines=2, roles=("prefill", "prefill"))
    with pytest.raises(ValueError, match="autoscale"):
        FleetConfig(engines=2, roles=("prefill", "decode"),
                    autoscale=True, max_engines=4)
    with pytest.raises(ValueError, match="migration_transport"):
        FleetConfig(migration_transport="carrier")
    with pytest.raises(ValueError, match="max_handoff_retries"):
        FleetConfig(max_handoff_retries=0)
    # list from YAML coerces to tuple
    cfg = FleetConfig.from_config(
        {"engines": 2, "roles": ["prefill", "decode"]})
    assert cfg.roles == ("prefill", "decode")


def test_decode_role_gates_submit(serve_setup):
    eng = _engine(serve_setup, role="decode")
    with pytest.raises(RuntimeError, match="handoff-only"):
        eng.submit([3, 5, 7, 2], MAX_NEW)
    eng.close()
    with pytest.raises(ValueError, match="role"):
        _engine(serve_setup, role="verifier")


# ---------------------------------------------------------------------------
# ticket round-trip: bit-identity, refcounts, compile pinning
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sampling", [
    None,
    SamplingParams(temperature=0.8, top_k=20, seed=1234),
], ids=["greedy", "seeded-sampled"])
def test_migrate_mid_decode_resumes_bit_identical(serve_setup, sampling):
    """Export after 2 streamed tokens, install on a fresh decode-role
    engine, finish there: the merged stream equals the single-engine
    run exactly — the scatter restored the exact committed KV columns
    and the ``fold_in(seed, k)`` sampling stream is engine-independent."""
    prompt = [3, 5, 7, 2, 9, 4, 6, 8, 11, 13]
    ref = _engine(serve_setup)
    rid = ref.submit(prompt, MAX_NEW, sampling=sampling)
    _drain(ref)
    want = list(ref.result(rid).generated)
    assert len(want) == MAX_NEW
    ref.close()

    src = _engine(serve_setup)
    dst = _engine(serve_setup, role="decode")
    rid = src.submit(prompt, MAX_NEW, sampling=sampling)
    _run_to(src, rid, 2)
    streamed = list(src.result(rid).generated)

    mig = KVMigrator(MigrationConfig())
    moved = mig.migrate(src, rid, dst)
    # exactly-once: the source forgot the request, the target owns it
    assert rid not in src._results
    assert dst.result(rid) is moved
    assert list(moved.generated) == streamed     # nothing re-emitted
    _drain(dst)
    got = list(dst.result(rid).generated)
    assert got == want
    assert src._mig_stats["migrations"] == 0      # source only exports
    assert dst._mig_stats["migrations"] == 1
    assert dst._mig_stats["migrated_pages"] > 0
    # nothing leaked on either side
    _drain(src)
    src.scheduler.assert_consistent()
    dst.scheduler.assert_consistent()
    assert src.cache.allocator.used_count == 0
    assert dst.cache.allocator.used_count == 0
    src.close()
    dst.close()


def test_migrate_cow_shared_pages_keeps_refcounts(serve_setup):
    """Two same-prompt requests share prefix pages on the source (COW
    via the prefix cache). Migrating one must not disturb the stayer:
    export is read-only, release decrefs only the mover's references,
    and the target registers its fresh copies into its own cache at
    refcount 1 + indexed."""
    prompt = [3, 5, 7, 2, 9, 4, 6, 8]           # 2 full pages
    src = _engine(serve_setup)
    dst = _engine(serve_setup, role="decode")
    warm = src.submit(prompt, MAX_NEW)           # registers the prefix
    _drain(src)
    del warm
    rid_a = src.submit(prompt, MAX_NEW)          # both alias the cached
    rid_b = src.submit(prompt, MAX_NEW)          # prompt pages
    _run_to(src, rid_a, 2)
    req_a, req_b = src.result(rid_a), src.result(rid_b)
    shared = set(req_a.pages) & set(req_b.pages)
    assert shared, "prefix cache should COW-share the prompt pages"
    before = {p: src.cache.allocator.refcount(p) for p in shared}

    moved = KVMigrator(MigrationConfig()).migrate(src, rid_a, dst)
    # stayer's shared pages lost exactly the mover's reference
    for p in shared:
        assert src.cache.allocator.refcount(p) == before[p] - 1
    src.scheduler.assert_consistent()
    # target owns fresh pages, refcount 1, committed ones cache-indexed
    committed = len(moved.prefix_tokens) - 1
    n_full = committed // PAGE
    for i, p in enumerate(moved.pages[:n_full]):
        assert dst.cache.allocator.refcount(p) == 1
        assert dst.prefix_cache.is_indexed(p)
    dst.scheduler.assert_consistent()

    _drain(src)
    _drain(dst)
    assert list(dst.result(rid_a).generated) \
        == list(src.result(rid_b).generated)    # same prompt, same tokens
    assert src.cache.allocator.used_count == 0
    assert dst.cache.allocator.used_count == 0
    src.close()
    dst.close()


def test_export_refuses_eviction_holes_and_counts(serve_setup):
    """A request that is not mid-decode (finished, queued, or evicted
    back to WAITING) has no committed-KV contract to export — the
    refusal is an error to the caller and a counter on the engine."""
    src = _engine(serve_setup)
    dst = _engine(serve_setup, role="decode")
    mig = KVMigrator(MigrationConfig())
    rid = src.submit([3, 5, 7, 2, 9], MAX_NEW)
    _drain(src)                                  # FINISHED: a hole
    with pytest.raises(MigrationError, match="mid-decode"):
        mig.migrate(src, rid, dst)
    with pytest.raises(MigrationError, match="unknown"):
        mig.export_ticket(src, 10 ** 9)
    assert src._mig_stats["failed_migrations"] == 2
    src.step()                                   # idle step mirrors
    snap = src.metrics.snapshot()
    assert snap["serving/migration/failed_migrations"] == 2
    assert snap["serving/migration/migrations"] == 0
    src.close()
    dst.close()


def test_import_and_export_compile_exactly_once(serve_setup):
    """The gather/scatter pair is fixed-shape (pad page ids route to
    the trash page): migrating requests of different lengths must not
    recompile either side."""
    src = _engine(serve_setup)
    dst = _engine(serve_setup, role="decode")
    mig = KVMigrator(MigrationConfig())
    for i, plen in enumerate((5, 9, 13)):        # 2, 3, 4 pages committed
        prompt = [3 + i] * plen
        rid = src.submit(prompt, MAX_NEW)
        _run_to(src, rid, 2)
        mig.migrate(src, rid, dst)
        assert src.export_compiles == 1
        assert dst.import_compiles == 1
        _drain(dst)                              # free the decode slot
    _drain(src)
    assert dst._mig_stats["migrations"] == 3
    assert src.cache.allocator.used_count == 0
    assert dst.cache.allocator.used_count == 0
    src.close()
    dst.close()


def test_host_transport_bounces_and_counts_bytes(serve_setup):
    src = _engine(serve_setup)
    dst = _engine(serve_setup, role="decode")
    rid = src.submit([1, 2, 3, 4, 5, 6, 7, 8], MAX_NEW)
    _run_to(src, rid, 2)
    KVMigrator(MigrationConfig("host")).migrate(src, rid, dst)
    _drain(dst)
    assert dst._mig_stats["host_bounce_bytes"] > 0
    snap = dst.metrics.snapshot()
    assert snap["serving/migration/host_bounce_bytes"] > 0
    src.close()
    dst.close()


# ---------------------------------------------------------------------------
# restore fast path: alias cached pages instead of re-prefilling
# ---------------------------------------------------------------------------

def test_restore_aliases_cached_pages_without_prefill(serve_setup):
    """When the prefix cache holds EVERY committed page, restore adopts
    straight into decode — zero prefill chunks — and still reproduces
    the original continuation bit-for-bit."""
    eng = _engine(serve_setup)
    prompt = [3, 5, 7, 2, 9, 4, 6, 8]            # page-aligned prompt
    rid = eng.submit(prompt, MAX_NEW)
    _drain(eng)
    full = list(eng.result(rid).generated)

    chunks_before = eng.metrics.prefill_chunks.value
    saved_before = eng.metrics.prefill_tokens_saved.value
    # committed = len(prompt) + 1 - 1 = 8: both pages sit in the cache
    restored = eng.restore(prompt, MAX_NEW, generated=full[:1],
                           arrival_time=0.0, rid=rid)
    assert restored.state.value == "decode"      # adopted, never queued
    _drain(eng)
    assert eng.metrics.prefill_chunks.value == chunks_before
    assert eng.metrics.prefill_tokens_saved.value \
        == saved_before + len(prompt)
    assert list(restored.generated) == full
    eng.scheduler.assert_consistent()
    assert eng.cache.allocator.used_count == 0
    eng.close()


# ---------------------------------------------------------------------------
# disaggregated fleet: bit-identity, exactly-once under chaos
# ---------------------------------------------------------------------------

ROLES = ("prefill", "decode", "decode")


def _prompts(n=12, seed=11):
    rs = np.random.RandomState(seed)
    return [[int(t) for t in rs.randint(3, 500, (10,))] for _ in range(n)]


def _serve(eng, prompts, sampling=None):
    params = sampling or [None] * len(prompts)
    rids = [eng.submit(p, MAX_NEW, sampling=s)
            for p, s in zip(prompts, params)]
    results = eng.run_until_drained(max_steps=5000)
    assert all(results[r].state in TERMINAL_STATES for r in rids)
    return [list(results[r].generated) for r in rids]


def _role_factory(serve_setup, **cfg_kw):
    def factory(slot):
        role = ROLES[slot] if slot < len(ROLES) else "mixed"
        return _engine(serve_setup, role=role, **cfg_kw)
    return factory


@pytest.mark.parametrize("sampled", [False, True],
                         ids=["greedy", "seeded-sampled"])
def test_disagg_fleet_bit_identical_to_single_engine(serve_setup,
                                                     sampled):
    """1 prefill + 2 decode members reproduce the single engine's
    tokens exactly; every request is handed off (the prefill member
    never decodes past its first token) and no member leaks a page."""
    prompts = _prompts()
    sampling = ([SamplingParams(temperature=0.8, top_k=20, seed=100 + i)
                 for i in range(len(prompts))] if sampled else None)
    single = _engine(serve_setup)
    want = _serve(single, prompts, sampling)
    single.close()

    router = FleetRouter(_role_factory(serve_setup),
                         FleetConfig(engines=3, roles=ROLES))
    got = _serve(router, prompts, sampling)
    migrations = sum(
        m.engine.metrics.snapshot()["serving/migration/migrations"]
        for m in router.members())
    for m in router.members():
        m.engine.scheduler.assert_consistent()
        assert m.engine.cache.allocator.used_count == 0
    router.close()
    assert got == want
    assert migrations == len(prompts)            # every request moved


def test_disagg_chaos_on_source_lands_requests_exactly_once(serve_setup):
    """The prefill member wedges and then dies mid-trace: supervised
    rebuild + replay re-runs only the requests whose journal entries
    still live on the source — already-handed-off requests moved with
    their entries, so every rid lands on exactly one member, nothing is
    lost, and the merged output still equals the fault-free fleet."""
    prompts = _prompts()
    sup_cfg = SupervisorConfig(watchdog_timeout_s=0.05,
                               watchdog_poll_s=0.01, max_restarts=3)
    clean_factory = _role_factory(serve_setup)

    clean = FleetRouter(clean_factory, FleetConfig(engines=3, roles=ROLES),
                        supervisor=sup_cfg)
    want = _serve(clean, prompts)
    clean.close()

    chaos_engine = _role_factory(
        serve_setup,
        fault_plan="engine_step=2:wedge:0.3;engine_step=4:device_error")

    def chaos_factory(slot):
        return chaos_engine(slot) if slot == 0 else clean_factory(slot)

    router = FleetRouter(chaos_factory, FleetConfig(engines=3, roles=ROLES),
                         supervisor=sup_cfg)
    rids = [router.submit(p, MAX_NEW) for p in prompts]
    results = router.run_until_drained(max_steps=5000)
    restarts = [m.sup.restarts for m in router.members()]
    # exactly-once: each rid's journal entry lives on exactly one member
    for rid in rids:
        holders = [m.slot for m in router.members()
                   if rid in m.sup.journal]
        assert len(holders) == 1, (rid, holders)
    got = [list(results[r].generated) for r in rids]
    lost = [r for r in rids if results[r].state not in TERMINAL_STATES]
    for m in router.members():
        assert m.engine.cache.allocator.used_count == 0
    router.close()
    assert lost == []
    assert restarts[0] >= 1 and restarts[1:] == [0, 0]
    assert got == want


def test_handoff_retry_bound_pins_requests_locally(serve_setup,
                                                   monkeypatch):
    """Every install refused: after ``max_handoff_retries`` passes the
    router stops re-offering each request (no unbounded refuse/re-insert
    cycle), ticks ``serving/migration/failed_handoffs`` once per
    request, and the requests finish decoding on their prefill member —
    the engine is decode-capable, the role is router policy — with
    tokens still equal to the single-engine run."""
    prompts = _prompts(n=4, seed=17)
    single = _engine(serve_setup)
    want = _serve(single, prompts)
    single.close()

    router = FleetRouter(_role_factory(serve_setup),
                         FleetConfig(engines=3, roles=ROLES,
                                     max_handoff_retries=2))

    def refuse(dst_engine, ticket):
        raise MigrationError("injected: sink refuses every install")

    monkeypatch.setattr(router.migrator, "install", refuse)
    got = _serve(router, prompts)
    assert got == want                   # placement-independent tokens
    assert router.metrics.failed_handoffs.value == len(prompts)
    migrations = sum(
        m.engine.metrics.snapshot()["serving/migration/migrations"]
        for m in router.members())
    assert migrations == 0               # nothing ever moved
    # bookkeeping retired once the pinned requests finished
    assert not router._handoff_pinned and not router._handoff_fails
    for m in router.members():
        m.engine.scheduler.assert_consistent()
        assert m.engine.cache.allocator.used_count == 0
    router.close()


def test_scale_down_migrates_running_work_zero_loss(serve_setup):
    """Retiring a mixed member mid-burst ships its in-flight decodes to
    the surviving member as KV tickets (no re-prefill) and nothing is
    lost."""
    model_prompts = _prompts(n=6)

    def factory(slot):
        return _engine(serve_setup)
    single = factory(0)
    want = _serve(single, model_prompts)
    single.close()

    router = FleetRouter(factory, FleetConfig(engines=2))
    rids = [router.submit(p, MAX_NEW) for p in model_prompts]
    for _ in range(3):                           # some requests mid-decode
        router.step()
    victim = next(m for m in router.members()
                  if m.engine.scheduler.running)
    router.scale_down(victim)
    results = router.run_until_drained(max_steps=5000)
    survivor = router.members()[0]
    migrated = survivor.engine.metrics.snapshot()[
        "serving/migration/migrations"]
    router.close()
    assert all(results[r].state in TERMINAL_STATES for r in rids)
    assert [list(results[r].generated) for r in rids] == want
    assert migrated > 0                          # running work moved as KV
