"""Async input pipeline: background prefetch + lazy packing (VERDICT
round-1 item 5). The reference gets this from torch DataLoader workers
(num_workers, reference config/sft_config.yaml:14); here it is a bounded
producer/consumer thread plus length-only lazy packing."""
import threading
import time

import numpy as np
import pytest

from dla_tpu.data.iterator import ShardedBatchIterator
from dla_tpu.data.prefetch import PrefetchIterator


class CountingDataset:
    """Tiny dataset that records __getitem__ calls and can be slowed."""

    def __init__(self, n=64, delay=0.0):
        self.n = n
        self.delay = delay
        self.calls = 0

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        self.calls += 1
        if self.delay:
            time.sleep(self.delay)
        return {"x": np.full((4,), i, np.int32)}

    def collate(self, examples):
        return {"x": np.stack([e["x"] for e in examples])}


def test_prefetch_produces_ahead_of_consumption():
    """While the consumer holds batch N, the worker must already have
    produced batches N+1..N+depth — the definition of overlap."""
    ds = CountingDataset(64)
    src = ShardedBatchIterator(ds, 4, seed=0)
    pf = PrefetchIterator(src, prefetch=3)
    try:
        next(pf)  # starts the worker
        deadline = time.monotonic() + 5.0
        while pf.produced < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        # 1 consumed + 3 queued
        assert pf.produced >= 4, f"only {pf.produced} batches produced"
    finally:
        pf.close()


def test_prefetch_overlaps_slow_dataset():
    """With a slow producer and a slow consumer, total time must be close
    to max(producer, consumer), not their sum."""
    per_item = 0.01
    batch = 4
    steps = 8
    ds = CountingDataset(64, delay=per_item)
    pf = PrefetchIterator(ShardedBatchIterator(ds, batch, seed=0), prefetch=2)
    step_time = per_item * batch  # consumer work == producer work per batch
    try:
        it = iter(pf)
        next(it)  # warm the pipeline
        t0 = time.monotonic()
        for _ in range(steps):
            time.sleep(step_time)  # simulated device step
            next(it)
        elapsed = time.monotonic() - t0
    finally:
        pf.close()
    serial = 2 * step_time * steps  # no-overlap time: produce + consume
    assert elapsed < serial * 0.8, (
        f"prefetch gave no overlap: {elapsed:.3f}s vs serial {serial:.3f}s")


def test_prefetch_state_tracks_consumed_not_produced():
    """Checkpoint state must reflect the last batch the trainer saw, not
    the read-ahead position — else resume skips queued batches."""
    ds = CountingDataset(64)
    src = ShardedBatchIterator(ds, 4, seed=3)
    pf = PrefetchIterator(src, prefetch=4)
    try:
        got = [next(pf) for _ in range(3)]
        deadline = time.monotonic() + 5.0
        while pf.produced < 6 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert src.state_dict()["step_in_epoch"] > 3  # source ran ahead
        state = pf.state_dict()
        assert state["step_in_epoch"] == 3
    finally:
        pf.close()

    # resuming from that state yields exactly the 4th batch of a cold run
    cold = iter(ShardedBatchIterator(CountingDataset(64), 4, seed=3))
    for _ in range(3):
        next(cold)
    want = next(cold)
    resumed_src = ShardedBatchIterator(CountingDataset(64), 4, seed=3)
    pf2 = PrefetchIterator(resumed_src, prefetch=4)
    pf2.load_state_dict(state)
    try:
        got4 = next(pf2)
    finally:
        pf2.close()
    np.testing.assert_array_equal(got4["x"], want["x"])
    del got


def test_prefetch_propagates_worker_errors():
    class Boom:
        def __iter__(self):
            yield {"x": np.zeros(1)}
            raise RuntimeError("worker died")

    pf = PrefetchIterator(Boom(), prefetch=2)
    try:
        next(pf)
        with pytest.raises(RuntimeError, match="worker died"):
            next(pf)
    finally:
        pf.close()


def test_prefetch_finite_source_stops():
    class Finite:
        def __iter__(self):
            for i in range(3):
                yield i

    pf = PrefetchIterator(Finite(), prefetch=2)
    try:
        assert list(pf) == [0, 1, 2]
    finally:
        pf.close()


def test_lazy_packing_matches_eager_and_defers_tokenization(tmp_path):
    from dla_tpu.data.jsonl import write_jsonl
    from dla_tpu.data.loaders import build_instruction_dataset
    from dla_tpu.data.packing import PackedInstructionDataset
    from dla_tpu.data.tokenizers import ByteTokenizer

    p = tmp_path / "sft.jsonl"
    write_jsonl(p, [{"prompt": f"q{i}" * (1 + i % 7),
                     "response": f"a{i}" * (1 + i % 5)} for i in range(40)])
    cfg = {"source": "local", "train_path": str(p), "max_seq_length": 48}
    base = build_instruction_dataset(cfg, ByteTokenizer(), split="train")

    eager = PackedInstructionDataset(base, 48, lazy=False)
    lazy = PackedInstructionDataset(base, 48, lazy=True)
    assert len(eager) == len(lazy)
    assert eager.packing_efficiency() == lazy.packing_efficiency()
    for i in range(len(eager)):
        a, b = eager[i], lazy[i]
        for key in a:
            np.testing.assert_array_equal(a[key], b[key])
    # lazy __init__ holds no tokenized corpus
    assert lazy._examples == []


def test_trainer_fit_uses_prefetch(tmp_path):
    """End-to-end: Trainer.fit with data.prefetch wraps the iterator, the
    run completes, and the checkpoint data_state matches consumed steps."""
    import jax
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.losses import cross_entropy_loss
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.training.trainer import Trainer

    mesh = build_mesh(MeshConfig(data=1, fsdp=-1, model=1, sequence=1))
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        logits = model.apply(p, batch["input_ids"],
                             attention_mask=batch["attention_mask"])
        loss, _ = cross_entropy_loss(logits, batch["labels"])
        return loss, {}

    class LMDataset(CountingDataset):
        def __getitem__(self, i):
            self.calls += 1
            ids = np.full((8,), (i % 100) + 1, np.int32)
            return {"input_ids": ids,
                    "attention_mask": np.ones(8, np.int32),
                    "labels": ids}

        def collate(self, examples):
            return {k: np.stack([e[k] for e in examples])
                    for k in examples[0]}

    config = {
        "experiment_name": "pf_test",
        "optimization": {"total_batch_size": 8, "micro_batch_size": 1,
                         "learning_rate": 1e-3, "max_train_steps": 3,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "data": {"prefetch": 2},
        "logging": {"output_dir": str(tmp_path / "ck"), "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    with jax.sharding.set_mesh(mesh):
        trainer = Trainer(config=config, mesh=mesh, loss_fn=loss_fn,
                          params=params,
                          param_specs=model.partition_specs())
        it = ShardedBatchIterator(LMDataset(64), 8, seed=0)
        trainer.fit(it, rng=jax.random.key(1), data_state=it.state_dict)

    from dla_tpu.checkpoint import load_tree_numpy
    _, aux = load_tree_numpy(tmp_path / "ck")
    assert aux["step"] == 3
    assert aux["data_state"]["step_in_epoch"] == 3
