"""Unit tests for ops: losses vs hand-computed values, attention vs naive."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.ops.attention import causal_attention, decode_attention
from dla_tpu.ops.losses import (
    IGNORE_INDEX,
    cross_entropy_loss,
    dpo_loss,
    kl_distill_loss,
    pairwise_reward_loss,
    ppo_clip_loss,
    reinforce_loss,
    sequence_logprob_mean,
    token_logprobs,
)
from dla_tpu.ops.norms import rms_norm
from dla_tpu.ops.rotary import apply_rotary, rotary_angles
from dla_tpu.ops.sampling import sample_token, top_k_mask, top_p_mask


def test_rms_norm_matches_numpy():
    x = np.random.RandomState(0).randn(2, 5, 8).astype(np.float32)
    w = np.random.RandomState(1).rand(8).astype(np.float32)
    got = rms_norm(jnp.asarray(x), jnp.asarray(w), eps=1e-5)
    want = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-5) * w
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_rotary_norm_preserving_and_position_zero_identity():
    x = jnp.asarray(np.random.RandomState(0).randn(1, 4, 2, 8).astype(np.float32))
    pos = jnp.arange(4)[None, :]
    cos, sin = rotary_angles(pos, 8)
    y = apply_rotary(x, cos, sin)
    # norms preserved (rotation)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(y), axis=-1), rtol=1e-5)
    # position 0 is identity
    np.testing.assert_allclose(np.asarray(x[:, 0]), np.asarray(y[:, 0]), rtol=1e-6)


def test_causal_attention_matches_naive():
    rs = np.random.RandomState(0)
    b, t, h, d = 2, 6, 4, 8
    q = jnp.asarray(rs.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rs.randn(b, t, h, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, t, h, d).astype(np.float32))
    got = np.asarray(causal_attention(q, k, v))

    qn, kn, vn = (np.asarray(a) for a in (q, k, v))
    want = np.zeros_like(qn)
    for bi in range(b):
        for hi in range(h):
            s = (qn[bi, :, hi] @ kn[bi, :, hi].T) / np.sqrt(d)
            mask = np.tril(np.ones((t, t), bool))
            s = np.where(mask, s, -1e30)
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            want[bi, :, hi] = p @ vn[bi, :, hi]
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_gqa_matches_repeated_kv():
    rs = np.random.RandomState(1)
    b, t, h, kh, d = 1, 5, 4, 2, 8
    q = jnp.asarray(rs.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rs.randn(b, t, kh, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, t, kh, d).astype(np.float32))
    got = causal_attention(q, k, v)
    # repeat kv heads to full h and compare
    k_full = jnp.repeat(k, h // kh, axis=2)
    v_full = jnp.repeat(v, h // kh, axis=2)
    want = causal_attention(q, k_full, v_full)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


@pytest.mark.parametrize("window,softcap", [(None, 0.0), (7, 0.0),
                                            (None, 5.0), (7, 5.0)])
def test_chunked_causal_attention_matches_one_shot(window, softcap):
    """Query-chunked attention (the O(T*chunk) path for flash-ineligible
    models like gemma-2) == one-shot causal_attention, forward and
    gradient, with windows/softcap/segments/custom scale."""
    from dla_tpu.ops.attention import chunked_causal_attention

    rs = np.random.RandomState(3)
    b, t, h, kh, d = 2, 24, 4, 2, 8
    q = jnp.asarray(rs.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rs.randn(b, t, kh, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, t, kh, d).astype(np.float32))
    seg = jnp.asarray((np.arange(t)[None, :] >= 10).astype(np.int32)
                      .repeat(2, 0))
    seg_mask = (seg[:, :, None] == seg[:, None, :]).astype(jnp.int32)
    kw = dict(kv_segment_mask=seg_mask, window=window,
              logit_softcap=softcap, softmax_scale=8 ** -0.5)

    def f_chunk(q, k, v):
        return chunked_causal_attention(q, k, v, q_chunk=8, **kw)

    def f_full(q, k, v):
        return causal_attention(q, k, v, **kw)

    np.testing.assert_allclose(np.asarray(f_chunk(q, k, v)),
                               np.asarray(f_full(q, k, v)),
                               rtol=2e-5, atol=2e-6)
    gc = jax.grad(lambda *a: jnp.sum(f_chunk(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(lambda *a: jnp.sum(f_full(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gc, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


def test_chunked_attention_factored_mask_matches_materialized():
    """The factored 1-D metadata path (kv_valid + segment ids, per-chunk
    mask slabs — no [B,T,S] ever) must equal the caller-materialized
    kv_segment_mask path, forward and gradient."""
    from dla_tpu.ops.attention import chunked_causal_attention

    rs = np.random.RandomState(5)
    b, t, h, kh, d = 2, 24, 4, 2, 8
    q = jnp.asarray(rs.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rs.randn(b, t, kh, d).astype(np.float32))
    v = jnp.asarray(rs.randn(b, t, kh, d).astype(np.float32))
    valid = jnp.asarray((np.arange(t)[None, :]
                         < np.array([[t], [t - 5]])).astype(np.int32))
    seg = jnp.asarray((np.arange(t)[None, :] >= 9).astype(np.int32)
                      .repeat(2, 0) + 1)
    mask = (valid[:, None, :].astype(bool)
            & (seg[:, :, None] == seg[:, None, :]))

    def f_fac(q, k, v):
        return chunked_causal_attention(
            q, k, v, q_chunk=8, kv_valid=valid,
            q_segments=seg, kv_segments=seg, logit_softcap=5.0)

    def f_mat(q, k, v):
        return chunked_causal_attention(
            q, k, v, q_chunk=8, kv_segment_mask=mask, logit_softcap=5.0)

    np.testing.assert_allclose(np.asarray(f_fac(q, k, v)),
                               np.asarray(f_mat(q, k, v)),
                               rtol=1e-6, atol=1e-7)
    ga = jax.grad(lambda *a: jnp.sum(f_fac(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gb = jax.grad(lambda *a: jnp.sum(f_mat(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for x, y in zip(ga, gb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=1e-6)


def test_chunked_attention_pads_indivisible_lengths():
    """A T that doesn't divide into chunks is padded up, NOT bounced to
    the quadratic one-shot op (the memory bound must hold for every
    length); results still match exactly, forward and gradient."""
    from dla_tpu.ops.attention import chunked_causal_attention

    rs = np.random.RandomState(4)
    q = jnp.asarray(rs.randn(1, 10, 2, 8).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 10, 2, 8).astype(np.float32))
    v = jnp.asarray(rs.randn(1, 10, 2, 8).astype(np.float32))

    def f_chunk(q, k, v):
        return chunked_causal_attention(q, k, v, q_chunk=4)  # 10 % 4 != 0

    np.testing.assert_allclose(np.asarray(f_chunk(q, k, v)),
                               np.asarray(causal_attention(q, k, v)),
                               rtol=1e-5, atol=1e-6)
    gc = jax.grad(lambda *a: jnp.sum(f_chunk(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(lambda *a: jnp.sum(causal_attention(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gc, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("window", [None, 3])
def test_decode_attention_matches_concat_cache(window):
    """decode_attention over (un-updated cache + new k/v) must equal
    causal_attention over the cache with the new column appended — GQA,
    ragged validity holes, and sliding window included. This is the
    no-copy decode hot path's correctness contract."""
    rs = np.random.RandomState(0)
    b, s, h, kh, d = 2, 6, 4, 2, 8
    k_cache = jnp.asarray(rs.randn(b, s, kh, d).astype(np.float32))
    v_cache = jnp.asarray(rs.randn(b, s, kh, d).astype(np.float32))
    q = jnp.asarray(rs.randn(b, 1, h, d).astype(np.float32))
    k_new = jnp.asarray(rs.randn(b, 1, kh, d).astype(np.float32))
    v_new = jnp.asarray(rs.randn(b, 1, kh, d).astype(np.float32))
    # ragged: row 0 has 4 real columns, row 1 has 6, with a mid-row hole
    valid = jnp.asarray([[1, 1, 0, 1, 1, 0], [1, 1, 1, 1, 1, 1]], jnp.int32)
    kv_pos = jnp.asarray([[0, 1, 9, 2, 3, 9], [0, 1, 2, 3, 4, 5]], jnp.int32)
    q_pos = jnp.asarray([[4], [6]], jnp.int32)

    got = decode_attention(q, k_cache, v_cache, k_new, v_new,
                           kv_valid=valid, q_positions=q_pos,
                           kv_positions=kv_pos, window=window)

    cat_k = jnp.concatenate([k_cache, k_new], axis=1)
    cat_v = jnp.concatenate([v_cache, v_new], axis=1)
    cat_valid = jnp.concatenate([valid, jnp.ones((b, 1), jnp.int32)], axis=1)
    cat_pos = jnp.concatenate([kv_pos, q_pos], axis=1)
    want = causal_attention(q, cat_k, cat_v,
                            kv_segment_mask=cat_valid[:, None, :],
                            q_positions=q_pos, kv_positions=cat_pos,
                            window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_token_logprobs_vs_log_softmax():
    rs = np.random.RandomState(2)
    logits = jnp.asarray(rs.randn(2, 4, 10).astype(np.float32))
    targets = jnp.asarray(rs.randint(0, 10, (2, 4)))
    got = token_logprobs(logits, targets)
    want = np.take_along_axis(
        np.asarray(jax.nn.log_softmax(logits, -1)),
        np.asarray(targets)[..., None], -1)[..., 0]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_cross_entropy_ignores_masked_labels():
    rs = np.random.RandomState(3)
    logits = jnp.asarray(rs.randn(1, 5, 7).astype(np.float32))
    labels = jnp.asarray([[IGNORE_INDEX, IGNORE_INDEX, 3, 4, 5]])
    loss, n = cross_entropy_loss(logits, labels)
    assert int(n) == 3  # positions 2,3,4 of the shifted labels
    # hand-compute: logits[:, :-1] predict labels[:, 1:]
    lp = np.asarray(jax.nn.log_softmax(logits[:, :-1], -1))
    want = -(lp[0, 1, 3] + lp[0, 2, 4] + lp[0, 3, 5]) / 3
    np.testing.assert_allclose(float(loss), want, rtol=1e-5)


def test_sequence_logprob_mean_hand_case():
    # 2 tokens after shift, equal logits -> logp = -log(V) each
    v = 4
    logits = jnp.zeros((1, 3, v))
    ids = jnp.asarray([[1, 2, 3]])
    mask = jnp.asarray([[1, 1, 1]])
    got = float(sequence_logprob_mean(logits, ids, mask)[0])
    np.testing.assert_allclose(got, -np.log(v), rtol=1e-6)


def test_dpo_loss_reference_math():
    pc, pr = jnp.asarray([-1.0]), jnp.asarray([-2.0])
    rc, rr = jnp.asarray([-1.5]), jnp.asarray([-1.8])
    beta = 0.1
    loss, margin = dpo_loss(pc, pr, rc, rr, beta)
    want_margin = beta * ((pc - pr) - (rc - rr))
    want_loss = -np.log(1 / (1 + np.exp(-np.asarray(want_margin))))
    np.testing.assert_allclose(float(loss), float(want_loss[0]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(margin), np.asarray(want_margin), rtol=1e-6)


def test_dpo_label_smoothing_zero_is_identity():
    pc, pr = jnp.asarray([-1.0, -0.5]), jnp.asarray([-2.0, -0.7])
    rc, rr = jnp.asarray([-1.5, -0.6]), jnp.asarray([-1.8, -0.9])
    l0, _ = dpo_loss(pc, pr, rc, rr, 0.1, label_smoothing=0.0)
    l1, _ = dpo_loss(pc, pr, rc, rr, 0.1, label_smoothing=0.1)
    assert not np.allclose(float(l0), float(l1))


def test_pairwise_reward_loss():
    c, r = jnp.asarray([2.0, 0.0]), jnp.asarray([1.0, 1.0])
    got = float(pairwise_reward_loss(c, r))
    want = -np.mean(np.log(1 / (1 + np.exp(-np.asarray([1.0, -1.0])))))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_reinforce_loss_gradient_direction():
    # positive advantage should push logp up (negative loss gradient on logp)
    logp = jnp.asarray([-1.0])
    adv = jnp.asarray([2.0])
    g = jax.grad(lambda lp: reinforce_loss(lp, adv))(logp)
    assert float(g[0]) < 0  # increasing logp decreases loss


def test_ppo_clip_matches_unclipped_in_trust_region():
    logp = jnp.asarray([-1.0, -1.0])
    behav = jnp.asarray([-1.05, -1.0])
    adv = jnp.asarray([1.0, -1.0])
    loss, frac = ppo_clip_loss(logp, behav, adv, clip_ratio=0.2)
    ratio = np.exp(np.asarray(logp) - np.asarray(behav))
    want = -np.mean(ratio * np.asarray(adv))
    np.testing.assert_allclose(float(loss), want, rtol=1e-5)
    assert float(frac) == 0.0


def test_kl_distill_zero_when_teacher_equals_student():
    rs = np.random.RandomState(4)
    logits = jnp.asarray(rs.randn(2, 5, 11).astype(np.float32))
    mask = jnp.ones((2, 5))
    kl = float(kl_distill_loss(logits, [logits], mask))
    assert abs(kl) < 1e-5


def test_kl_distill_ensemble_averaging():
    rs = np.random.RandomState(5)
    a = jnp.asarray(rs.randn(1, 4, 6).astype(np.float32))
    b = jnp.asarray(rs.randn(1, 4, 6).astype(np.float32))
    s = jnp.asarray(rs.randn(1, 4, 6).astype(np.float32))
    mask = jnp.ones((1, 4))
    kl_ab = float(kl_distill_loss(s, [a, b], mask))
    # averaging probs, not logits: verify against manual computation
    import jax.nn as jnn
    pa = np.asarray(jnn.softmax(a[:, :-1], -1))
    pb = np.asarray(jnn.softmax(b[:, :-1], -1))
    pm = (pa + pb) / 2
    slp = np.asarray(jnn.log_softmax(s[:, :-1], -1))
    want = (pm * (np.log(pm + 1e-20) - slp)).sum(-1).mean()
    np.testing.assert_allclose(kl_ab, want, rtol=1e-4)


def test_top_k_mask():
    logits = jnp.asarray([[1.0, 3.0, 2.0, 0.5]])
    out = np.asarray(top_k_mask(logits, 2))
    assert out[0, 1] == 3.0 and out[0, 2] == 2.0
    assert out[0, 0] < -1e29 and out[0, 3] < -1e29


def test_top_p_mask_keeps_threshold_token():
    # probs ~ [0.7, 0.2, 0.1]; p=0.75 keeps first two
    logits = jnp.log(jnp.asarray([[0.7, 0.2, 0.1]]))
    out = np.asarray(top_p_mask(logits, 0.75))
    assert out[0, 0] > -1e29 and out[0, 1] > -1e29
    assert out[0, 2] < -1e29


def test_sample_token_greedy_and_temperature():
    logits = jnp.asarray([[0.0, 5.0, 1.0]])
    tok = sample_token(jax.random.key(0), logits, do_sample=False)
    assert int(tok[0]) == 1
    tok = sample_token(jax.random.key(0), logits, temperature=0.0)
    assert int(tok[0]) == 1
    # with sampling, draws follow the distribution (peaked logits -> mode)
    draws = [int(sample_token(jax.random.key(i), logits, temperature=1.0)[0])
             for i in range(20)]
    assert draws.count(1) >= 15


def test_block_decode_attention_matches_concat_reference():
    """block_decode_attention == causal_attention over [cache ++ block]
    with validity folded in, and degenerates to decode_attention at
    G=1 — windowed and unwindowed."""
    import numpy as np

    from dla_tpu.ops.attention import (
        block_decode_attention,
        causal_attention,
        decode_attention,
    )
    rng = np.random.RandomState(0)
    b, s, g, h, kh, d = 2, 24, 4, 4, 2, 16
    q = jnp.asarray(rng.randn(b, g, h, d), jnp.float32)
    kc = jnp.asarray(rng.randn(b, s, kh, d), jnp.float32)
    vc = jnp.asarray(rng.randn(b, s, kh, d), jnp.float32)
    kn = jnp.asarray(rng.randn(b, g, kh, d), jnp.float32)
    vn = jnp.asarray(rng.randn(b, g, kh, d), jnp.float32)
    valid = jnp.asarray(rng.rand(b, s) < 0.8)
    lengths = jnp.asarray([15, 9], jnp.int32)
    qpos = lengths[:, None] + jnp.arange(g)[None, :]
    kpos = jnp.broadcast_to(jnp.arange(s)[None], (b, s)).astype(jnp.int32)

    o1 = block_decode_attention(q[:, :1], kc, vc, kn[:, :1], vn[:, :1],
                                kv_valid=valid, q_positions=qpos[:, :1],
                                kv_positions=kpos)
    o1r = decode_attention(q[:, :1], kc, vc, kn[:, :1], vn[:, :1],
                           kv_valid=valid, q_positions=qpos[:, :1],
                           kv_positions=kpos)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o1r), atol=1e-5)

    k_all = jnp.concatenate([kc, kn], 1)
    v_all = jnp.concatenate([vc, vn], 1)
    valid_all = jnp.concatenate([valid, jnp.ones((b, g), bool)], 1)
    pos_all = jnp.concatenate([kpos, qpos], 1)
    segmask = jnp.broadcast_to(valid_all[:, None, :], (b, g, s + g))
    for win in (None, 6):
        ref = causal_attention(q, k_all, v_all, kv_segment_mask=segmask,
                               q_positions=qpos, kv_positions=pos_all,
                               window=win)
        out = block_decode_attention(q, kc, vc, kn, vn, kv_valid=valid,
                                     q_positions=qpos, kv_positions=kpos,
                                     window=win)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, err_msg=f"win={win}")
