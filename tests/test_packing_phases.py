"""Packing beyond SFT (r4 VERDICT item 7): preference pairs (DPO /
reward) and teacher rollouts (distill) pack into fixed rows with
loss-equivalence to the unpacked batches.

The bar everywhere: the packed path must compute the SAME loss as the
unpacked path over the same examples — packing only removes pad FLOPs.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import yaml

from dla_tpu.data.datasets import PreferenceDataset, TeacherRolloutDataset
from dla_tpu.data.jsonl import write_jsonl
from dla_tpu.data.packing import (
    PackedPreferenceDataset,
    PackedTeacherDataset,
)
from dla_tpu.data.tokenizers import load_tokenizer
from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import Transformer
from dla_tpu.ops.fused_ce import (
    model_fused_segment_logprob,
    model_fused_sequence_logprob,
)
from dla_tpu.ops.losses import dpo_loss, pairwise_reward_loss


def _pref_records(n=24, seed=0):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        a, b = int(rng.integers(0, 30)), int(rng.integers(0, 30))
        recs.append({
            "prompt": f"add {a} {b}",
            "chosen": f"the answer is {a + b} ok" * int(rng.integers(1, 3)),
            "rejected": "no" * int(rng.integers(1, 8)),
        })
    return recs


def _pref_base(tmp_path, max_length=64, n=24):
    write_jsonl(tmp_path / "pref.jsonl", _pref_records(n=n))
    tok = load_tokenizer("byte")
    return PreferenceDataset(tok, max_length,
                             path=str(tmp_path / "pref.jsonl")), tok


def test_packed_preference_placement_invariants(tmp_path):
    """Every pair placed exactly once; both sides fit their rows; the
    (row, segment) coordinate aligns chosen with its own rejected."""
    base, _ = _pref_base(tmp_path)
    ds = PackedPreferenceDataset(base, 64, lazy=False)

    placed = sorted(i for row in ds.rows for i in row)
    assert placed == list(range(len(base)))
    for r, members in enumerate(ds.rows):
        assert ds.len_c[members].sum() <= 64
        assert ds.len_r[members].sum() <= 64
        item = ds[r]
        for j, i in enumerate(members, start=1):
            for side, lens in (("chosen", ds.len_c), ("rejected", ds.len_r)):
                seg = item[side]["segment_ids"]
                n_tok = int((seg == j).sum())
                assert n_tok == lens[i], (r, j, side)
                # segment j's tokens are the original example's tokens
                ids = item[side]["input_ids"][seg == j]
                want = base[i][side]["input_ids"][:64]
                np.testing.assert_array_equal(ids, want)
        assert item["pair_mask"].sum() == len(members)
    # collate stacks nested sides + the top-level pair mask
    batch = ds.collate([ds[0], ds[min(1, len(ds) - 1)]])
    assert batch["chosen"]["input_ids"].shape == (2, 64)
    assert batch["pair_mask"].shape == (2, ds.max_pairs)


def test_packed_dpo_loss_equivalence(tmp_path):
    """Packed DPO == unpacked DPO over the same pairs: per-segment mean
    logps equal per-sequence mean logps, and the pair_mask-weighted loss
    equals the plain mean."""
    base, tok = _pref_base(tmp_path, max_length=48)
    ds = PackedPreferenceDataset(base, 48, lazy=False)
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))

    # unpacked: every pair its own row
    def pad(ex):
        L = 48
        ids = np.full(L, tok.pad_token_id, np.int32)
        m = np.zeros(L, np.int32)
        n = ex["input_ids"].shape[0]
        ids[:n] = ex["input_ids"][:L]
        m[:min(n, L)] = 1
        return ids, m

    sides = {}
    for side in ("chosen", "rejected"):
        ids = np.stack([pad(base[i][side])[0] for i in range(len(base))])
        m = np.stack([pad(base[i][side])[1] for i in range(len(base))])
        sides[side] = model_fused_sequence_logprob(
            model, params, jnp.asarray(ids), jnp.asarray(m))
    want_loss, want_margin = dpo_loss(sides["chosen"], sides["rejected"],
                                      jax.lax.stop_gradient(sides["chosen"]) * 0,
                                      jax.lax.stop_gradient(sides["rejected"]) * 0,
                                      beta=0.1)

    # packed: all rows in one batch
    batch = ds.collate([ds[r] for r in range(len(ds))])
    logps = {}
    for side in ("chosen", "rejected"):
        sub = {k: jnp.asarray(v) for k, v in batch[side].items()}
        logps[side] = model_fused_segment_logprob(
            model, params, sub, ds.max_pairs)
    pv = jnp.asarray(batch["pair_mask"])
    got_loss, _ = dpo_loss(logps["chosen"], logps["rejected"],
                           logps["chosen"] * 0, logps["rejected"] * 0,
                           beta=0.1, valid=pv)

    # per-pair logp parity at the (row, segment) coordinate
    for r, members in enumerate(ds.rows):
        for j, i in enumerate(members, start=1):
            for side in ("chosen", "rejected"):
                np.testing.assert_allclose(
                    float(logps[side][r, j - 1]), float(sides[side][i]),
                    rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=2e-4, atol=2e-6)


@pytest.mark.parametrize("pooling", ["last_token", "mean"])
def test_packed_reward_pooling_equivalence(tmp_path, pooling):
    """Per-segment reward pooling == per-sequence pooling for the same
    sequences, both pooling modes, plus masked pairwise-loss parity."""
    from dla_tpu.models.reward import RewardModel

    base, tok = _pref_base(tmp_path, max_length=48, n=12)
    ds = PackedPreferenceDataset(base, 48, lazy=False)
    cfg = get_model_config("tiny")
    rm = RewardModel(cfg, pooling=pooling)
    params = rm.init(jax.random.key(1))

    batch = ds.collate([ds[r] for r in range(len(ds))])
    rewards = {}
    for side in ("chosen", "rejected"):
        sub = batch[side]
        rewards[side] = rm.apply(
            params, jnp.asarray(sub["input_ids"]),
            jnp.asarray(sub["attention_mask"]),
            segment_ids=jnp.asarray(sub["segment_ids"]),
            n_segments=ds.max_pairs)

    L = 48
    for r, members in enumerate(ds.rows):
        for j, i in enumerate(members, start=1):
            for side in ("chosen", "rejected"):
                ex = base[i][side]
                n = min(ex["input_ids"].shape[0], L)
                ids = np.full((1, L), tok.pad_token_id, np.int32)
                m = np.zeros((1, L), np.int32)
                ids[0, :n] = ex["input_ids"][:n]
                m[0, :n] = 1
                want = rm.apply(params, jnp.asarray(ids), jnp.asarray(m))
                np.testing.assert_allclose(
                    float(rewards[side][r, j - 1]), float(want[0]),
                    rtol=2e-4, atol=2e-4)

    pv = jnp.asarray(batch["pair_mask"])
    got = pairwise_reward_loss(rewards["chosen"], rewards["rejected"],
                               valid=pv)
    flat_c = rewards["chosen"][pv > 0]
    flat_r = rewards["rejected"][pv > 0]
    want = pairwise_reward_loss(flat_c, flat_r)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-6)


def _teacher_records(n=20, seed=3):
    rng = np.random.default_rng(seed)
    return [{
        "prompt": f"q {i}",
        "teacher_response": "a" * int(rng.integers(2, 12)),
        "reward": float(rng.uniform(0, 1)),
    } for i in range(n)]


def test_packed_teacher_dataset_reward_and_labels(tmp_path):
    write_jsonl(tmp_path / "teach.jsonl", _teacher_records())
    tok = load_tokenizer("byte")
    base = TeacherRolloutDataset(tok, 48, path=str(tmp_path / "teach.jsonl"))
    ds = PackedTeacherDataset(base, 48, lazy=False)

    placed = sorted(i for row in ds.rows for i in row)
    assert placed == list(range(len(base)))
    for r, members in enumerate(ds.rows):
        item = ds[r]
        # token-weighted row reward preserves the corpus token-mean
        w = ds.lengths[members].astype(np.float64)
        want = float((w * ds.rewards[members]).sum() / w.sum())
        np.testing.assert_allclose(float(item["reward"]), want, rtol=1e-5)
        # every segment's first label is IGNOREd (next-token shift guard)
        seg = item["segment_ids"]
        for j in range(1, len(members) + 1):
            first = int(np.argmax(seg == j))
            assert item["labels"][first] == -100


def test_packed_distill_ce_equivalence(tmp_path):
    """Packed distill-CE == unpacked distill-CE: both are token-means
    over the identical valid-target set."""
    from dla_tpu.ops.fused_ce import fused_cross_entropy_loss

    write_jsonl(tmp_path / "teach.jsonl", _teacher_records())
    tok = load_tokenizer("byte")
    base = TeacherRolloutDataset(tok, 48, path=str(tmp_path / "teach.jsonl"))
    ds = PackedTeacherDataset(base, 48, lazy=False)
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(2))
    w, bias = model.unembed_params(params)

    # unpacked token-SUM and count (fused CE is sum/n; equivalence of the
    # means needs the global token pool, not a mean of per-row means)
    L = 48
    ids = np.full((len(base), L), tok.pad_token_id, np.int32)
    m = np.zeros((len(base), L), np.int32)
    labels = np.full((len(base), L), -100, np.int32)
    for i in range(len(base)):
        ex = base[i]
        n = min(ex["input_ids"].shape[0], L)
        ids[i, :n] = ex["input_ids"][:n]
        m[i, :n] = 1
        labels[i, :n] = ex["labels"][:n]
    h = model.hidden_states(params, jnp.asarray(ids),
                            attention_mask=jnp.asarray(m))
    want, n_want = fused_cross_entropy_loss(h, w, jnp.asarray(labels),
                                            bias=bias)

    batch = ds.collate([ds[r] for r in range(len(ds))])
    hp = model.hidden_states(params, jnp.asarray(batch["input_ids"]),
                             attention_mask=jnp.asarray(
                                 batch["attention_mask"]),
                             segment_ids=jnp.asarray(batch["segment_ids"]))
    got, n_got = fused_cross_entropy_loss(hp, w,
                                          jnp.asarray(batch["labels"]),
                                          bias=bias)
    # same token pool: packed drops each segment's first label, unpacked
    # never targets position 0 — identical valid counts
    assert int(n_got) == int(n_want)
    np.testing.assert_allclose(float(got), float(want),
                               rtol=2e-4, atol=2e-5)


def test_packed_distill_kl_equivalence(tmp_path):
    """Packed distill-KL == unpacked distill-KL through the REAL
    make_distill_loss (pins the segment-start KL mask construction in
    train_distill.py): both are token-means over the identical
    valid-target set, with the teacher forward segment-masked too."""
    from dla_tpu.training.train_distill import make_distill_loss

    write_jsonl(tmp_path / "teach.jsonl", _teacher_records())
    tok = load_tokenizer("byte")
    base = TeacherRolloutDataset(tok, 48, path=str(tmp_path / "teach.jsonl"))
    ds = PackedTeacherDataset(base, 48, lazy=False)
    student = Transformer(get_model_config("tiny"))
    teacher = Transformer(get_model_config("tiny"))
    sp = student.init(jax.random.key(4))
    tp = teacher.init(jax.random.key(5))

    loss_fn = make_distill_loss(student, [teacher], use_kl=True,
                                temperature=1.0)
    frozen = {"teacher_0": tp}

    # unpacked: one row per example
    L = 48
    n = len(base)
    ids = np.full((n, L), tok.pad_token_id, np.int32)
    m = np.zeros((n, L), np.int32)
    rewards = np.zeros((n,), np.float32)
    for i in range(n):
        ex = base[i]
        k = min(ex["input_ids"].shape[0], L)
        ids[i, :k] = ex["input_ids"][:k]
        m[i, :k] = 1
        rewards[i] = ex["reward"]
    want, _ = loss_fn(sp, frozen, {
        "input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(m),
        "reward": jnp.asarray(rewards)}, jax.random.key(0))

    batch = ds.collate([ds[r] for r in range(len(ds))])
    got, _ = loss_fn(sp, frozen, {
        "input_ids": jnp.asarray(batch["input_ids"]),
        "attention_mask": jnp.asarray(batch["attention_mask"]),
        "segment_ids": jnp.asarray(batch["segment_ids"]),
        "reward": jnp.asarray(batch["reward"])}, jax.random.key(0))
    np.testing.assert_allclose(float(got), float(want),
                               rtol=2e-4, atol=2e-5)


def test_packed_dpo_end_to_end(tmp_path):
    """train_dpo with data.packing: true on the 8-device CPU mesh: runs,
    logs pair-weighted metrics, loss finite and falling."""
    import json

    from dla_tpu.training.train_dpo import main

    write_jsonl(tmp_path / "pref.jsonl", _pref_records(n=48))
    cfg = {
        "experiment_name": "dpo_packed_smoke",
        "seed": 0,
        "model": {"model_name_or_path": "tiny", "tokenizer": "byte",
                  "max_seq_length": 64, "beta": 0.1},
        "data": {"source": "local", "packing": True,
                 "train_path": str(tmp_path / "pref.jsonl")},
        "optimization": {
            "total_batch_size": 8, "micro_batch_size": 2,
            "learning_rate": 1e-3, "warmup_steps": 2,
            "max_train_steps": 8, "lr_scheduler": "cosine",
            "max_grad_norm": 1.0,
        },
        "logging": {
            "output_dir": str(tmp_path / "ckpt"),
            "log_dir": str(tmp_path / "logs"),
            "log_every_steps": 2, "save_every_steps": 0,
        },
        "hardware": {
            "gradient_accumulation_steps": 2,
            "mesh": {"data": 2, "fsdp": 2, "model": 2},
        },
    }
    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump(cfg))
    main(["--config", str(p)])
    losses = []
    with open(tmp_path / "logs" / "metrics.jsonl") as fh:
        for line in fh:
            rec = json.loads(line)
            if "train/loss_instant" in rec:
                losses.append(rec["train/loss_instant"])
    assert losses and np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_packed_reward_end_to_end(tmp_path):
    """train_reward with data.packing: true learns preferences."""
    import json

    from dla_tpu.training.train_reward import main

    write_jsonl(tmp_path / "pref.jsonl", _pref_records(n=48))
    cfg = {
        "experiment_name": "reward_packed_smoke",
        "seed": 0,
        "model": {"base_model_name_or_path": "tiny", "tokenizer": "byte",
                  "max_seq_length": 64, "pooling": "last_token"},
        "data": {"source": "local", "packing": True,
                 "train_path": str(tmp_path / "pref.jsonl")},
        "optimization": {
            "total_batch_size": 8, "micro_batch_size": 2,
            "learning_rate": 2e-3, "warmup_steps": 2,
            "max_train_steps": 10, "lr_scheduler": "cosine",
            "max_grad_norm": 1.0,
        },
        "logging": {
            "output_dir": str(tmp_path / "ckpt"),
            "log_dir": str(tmp_path / "logs"),
            "log_every_steps": 2, "save_every_steps": 0,
        },
        "hardware": {
            "gradient_accumulation_steps": 2,
            "mesh": {"data": 2, "fsdp": 2, "model": 2},
        },
    }
    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump(cfg))
    main(["--config", str(p)])
    losses = []
    with open(tmp_path / "logs" / "metrics.jsonl") as fh:
        for line in fh:
            rec = json.loads(line)
            if "train/loss_instant" in rec:
                losses.append(rec["train/loss_instant"])
    assert losses and np.isfinite(losses).all()


def test_packed_distill_end_to_end(tmp_path):
    """train_distill (CE mode) with data.packing: true trains."""
    import json

    from dla_tpu.training.train_distill import main

    write_jsonl(tmp_path / "teach.jsonl", _teacher_records(n=200))
    cfg = {
        "experiment_name": "distill_packed_smoke",
        "seed": 0,
        "model": {"student_model_name_or_path": "tiny",
                  "tokenizer": "byte", "max_seq_length": 64},
        "data": {"source": "local", "packing": True,
                 "teacher_samples_path": str(tmp_path / "teach.jsonl")},
        "optimization": {
            "total_batch_size": 16, "micro_batch_size": 2,
            "learning_rate": 1e-3, "warmup_steps": 2,
            "max_train_steps": 8, "lr_scheduler": "cosine",
            "max_grad_norm": 1.0,
        },
        "logging": {
            "output_dir": str(tmp_path / "ckpt"),
            "log_dir": str(tmp_path / "logs"),
            "log_every_steps": 2, "save_every_steps": 0,
        },
        "hardware": {
            "gradient_accumulation_steps": 2,
            "mesh": {"data": 2, "fsdp": 2, "model": 2},
        },
    }
    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump(cfg))
    main(["--config", str(p)])
    losses = []
    with open(tmp_path / "logs" / "metrics.jsonl") as fh:
        for line in fh:
            rec = json.loads(line)
            if "train/loss_instant" in rec:
                losses.append(rec["train/loss_instant"])
    assert losses and np.isfinite(losses).all()
    assert losses[-1] < losses[0]
