"""Gemma-1 architecture: logits parity with transformers'
GemmaForCausalLM ((1+w) RMSNorm folded at import, gated GELU-tanh MLP,
sqrt(hidden)-scaled embeddings, tied unembedding), plus export
round-trip."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_gemma_dir(tmp_path_factory):
    from transformers import GemmaConfig, GemmaForCausalLM
    cfg = GemmaConfig(
        vocab_size=160, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=16, max_position_embeddings=64, rms_norm_eps=1e-6,
        rope_theta=10000.0, hidden_act="gelu_pytorch_tanh",
        tie_word_embeddings=True)
    torch.manual_seed(0)
    model = GemmaForCausalLM(cfg).eval()
    d = tmp_path_factory.mktemp("hf_gemma")
    model.save_pretrained(str(d), safe_serialization=True)
    return d, model


def test_gemma_import_matches_hf_logits(tiny_gemma_dir):
    d, hf_model = tiny_gemma_dir
    import jax.numpy as jnp

    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer

    hf_cfg = read_hf_config(d)
    cfg = hf_config_to_model_config(
        hf_cfg, dtype="float32", param_dtype="float32", remat="none")
    assert cfg.arch == "gemma"
    assert cfg.tie_embeddings and cfg.num_kv_heads == 1
    assert cfg.head_dim_ == 16
    params = import_hf_weights(d, cfg)
    model = Transformer(cfg)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 160, (2, 10))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_gemma_decode_matches_forward(tiny_gemma_dir):
    """The gemma embed scaling and MQA cache reach the decode path too."""
    d, _ = tiny_gemma_dir
    import jax
    import jax.numpy as jnp

    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer

    cfg = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none")
    params = import_hf_weights(d, cfg)
    model = Transformer(cfg)
    del jax

    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(1, 160, (1, 6)), jnp.int32)
    mask = jnp.ones((1, 6), jnp.int32)
    logits, cache = model.start_decode(params, ids, mask, 3)
    toks = []
    for _ in range(3):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        toks.append(int(tok[0]))
        logits, cache = model.decode_step(params, cache, tok)

    seq = list(np.asarray(ids[0]))
    want = []
    for _ in range(3):
        full = model.apply(params, jnp.asarray([seq], jnp.int32))
        nxt = int(np.argmax(np.asarray(full[0, -1])))
        want.append(nxt)
        seq.append(nxt)
    assert toks == want


def test_gemma_export_roundtrip(tmp_path, tiny_gemma_dir):
    d, hf_model = tiny_gemma_dir
    import jax
    import jax.numpy as jnp

    from dla_tpu.models.hf_export import export_hf_weights
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer

    cfg = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none")
    params = import_hf_weights(d, cfg)
    out = export_hf_weights(params, cfg, tmp_path / "hf_gemma_out")

    hf_cfg2 = read_hf_config(out)
    assert hf_cfg2["model_type"] == "gemma"
    assert hf_cfg2["hidden_act"] == "gelu_pytorch_tanh"
    params2 = import_hf_weights(out, hf_config_to_model_config(
        hf_cfg2, dtype="float32", param_dtype="float32", remat="none"))
    for a, b in zip(jax.tree.leaves(jax.tree.map(np.asarray, params)),
                    jax.tree.leaves(params2)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)

    # and transformers loads the exported dir with identical logits
    from transformers import GemmaForCausalLM
    model2 = GemmaForCausalLM.from_pretrained(
        str(out), torch_dtype=torch.float32).eval()
    rs = np.random.RandomState(2)
    ids = rs.randint(0, 160, (1, 8))
    ours = np.asarray(Transformer(cfg).apply(
        params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = model2(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_gemma_sharded_matches_single_device(tiny_gemma_dir):
    """Gemma's scaled embeddings + MQA survive the mesh: sharded forward
    equals single-device (MQA kv=1 can't shard over model, so the flash
    guard replicates — values must still match)."""
    d, _ = tiny_gemma_dir
    import jax
    import jax.numpy as jnp

    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.parallel.sharding import sharding_tree

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none")
    params = import_hf_weights(d, cfg)
    model = Transformer(cfg)
    rs = np.random.RandomState(5)
    ids = jnp.asarray(rs.randint(1, 160, (4, 8)), jnp.int32)

    want = model.apply(params, ids)
    mesh = build_mesh(MeshConfig(data=2, fsdp=4, model=1, sequence=1))
    with jax.sharding.set_mesh(mesh):
        sharded = jax.device_put(
            params, sharding_tree(model.partition_specs(), mesh))
        got = jax.jit(lambda p: model.apply(p, ids))(sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-4)


def test_gemma_lora_adapters_train(tiny_gemma_dir):
    """The gemma arch composes with the LoRA machinery: adapters over a
    frozen gemma base take gradient steps and the merged tree matches
    base+adapter math."""
    d, _ = tiny_gemma_dir
    import jax
    import jax.numpy as jnp

    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.fused_ce import model_fused_ce

    cfg = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none", lora_r=4)
    base = import_hf_weights(d, cfg)
    model = Transformer(cfg)
    adapters = model.init_lora(jax.random.key(0))

    rs = np.random.RandomState(0)
    batch = {
        "input_ids": jnp.asarray(rs.randint(1, 160, (2, 16)), jnp.int32),
        "attention_mask": jnp.ones((2, 16), jnp.int32),
        "labels": jnp.asarray(rs.randint(1, 160, (2, 16)), jnp.int32),
    }

    def loss(ad):
        return model_fused_ce(model, base, batch, lora=ad)[0]

    l0 = float(loss(adapters))
    grads = jax.grad(loss)(adapters)
    # gradient flows into every adapter leaf
    for leaf in jax.tree.leaves(
            jax.tree.map(lambda g: float(jnp.abs(g).sum()), grads)):
        assert np.isfinite(leaf)
    stepped = jax.tree.map(lambda a, g: a - 0.5 * g, adapters, grads)
    assert float(loss(stepped)) < l0  # a step downhill

    merged = model.merge_lora(base, stepped)
    out_m = model.apply(merged, batch["input_ids"])
    out_a = model.apply(base, batch["input_ids"], lora=stepped)
    np.testing.assert_allclose(np.asarray(out_m), np.asarray(out_a),
                               rtol=2e-4, atol=2e-5)
