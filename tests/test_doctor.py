"""dla-doctor (tools/dla_doctor.py): offline correlation of anomaly
postmortems against ring events, Prometheus dumps, and bench snapshots
— ranked most-likely-cause first, emitted as dla-report/1.

The committed fixture under tests/fixtures/doctor_run/ is the same one
``scripts/lint.sh`` self-checks at commit time; these tests pin its
diagnosis in detail plus the scoring/correlation behaviour on synthetic
runs, and the new telemetry/xla + telemetry/anomaly names through the
metrics tooling (tools/check_metric_names.py, tools/metrics_diff.py).
"""
import json

import pytest

from dla_tpu.analysis.report import validate_report
from tools.dla_doctor import (
    SELF_CHECK_DIR,
    correlate_anomaly,
    diagnose,
    load_run,
    load_runs,
    main,
    self_check,
)


# ---------------------------------------------------------------------------
# the committed fixture: known diagnosis, schema-valid report
# ---------------------------------------------------------------------------

def test_fixture_diagnosis_ranks_checkpoint_stall_first():
    run = load_run(SELF_CHECK_DIR)
    assert len(run["postmortems"]) == 1
    assert run["metrics"]          # the .prom dump parsed
    findings = diagnose(run, SELF_CHECK_DIR)
    top = findings[0]
    assert top["rule"] == "anomaly-correlated"
    assert "checkpoint" in top["message"]
    assert "loadable" in top["message"]      # trace verified, not assumed
    rules = {f["rule"] for f in findings}
    # the Prometheus checks fired on the fixture's dump
    assert "metric-badput-checkpoint" in rules
    assert "metric-recompiles" in rules


def test_self_check_passes_on_committed_fixture(capsys):
    assert self_check() == 0
    assert "OK" in capsys.readouterr().out


def test_cli_json_output_is_valid_dla_report(capsys):
    rc = main([str(SELF_CHECK_DIR), "--format", "json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    validate_report(doc)           # schema shared with dla-lint et al.
    assert doc["tool"] == "dla-doctor"
    assert doc["summary"]["anomalies"] == 1
    assert doc["findings"][0]["rule"] == "anomaly-correlated"


def test_cli_text_output_and_exit_codes(tmp_path, capsys):
    rc = main([str(SELF_CHECK_DIR)])
    out = capsys.readouterr().out
    assert rc == 0 and "most likely cause first" in out
    # empty dir: clean diagnosis, still exit 0 (findings inform, not gate)
    rc = main([str(tmp_path)])
    assert rc == 0
    assert "clean" in capsys.readouterr().out
    # missing dir: usage error
    assert main([str(tmp_path / "nope")]) == 2


def test_self_check_fails_on_empty_dir(tmp_path, capsys):
    assert self_check(tmp_path) == 1
    assert "FAIL" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# correlation scoring on synthetic runs
# ---------------------------------------------------------------------------

def _pm(tmp_path, events, anomaly=None, name="postmortem_anomaly.json"):
    doc = {"reason": "anomaly", "events": events}
    if anomaly is not None:
        doc["anomaly"] = anomaly
    (tmp_path / name).write_text(json.dumps(doc))


def test_nearer_cause_outranks_heavier_far_one():
    events = [
        {"t": 1.0, "kind": "ckpt_retry", "step": 4},       # w=3.5, d=6
        {"t": 2.0, "kind": "ckpt_save_start", "step": 10},  # w=3.0, d=0
    ]
    causes = correlate_anomaly({"trigger_step": 10}, events, window=10)
    assert causes[0]["kind"] == "ckpt_save_start"
    assert causes[0]["score"] == pytest.approx(3.0)
    assert causes[1]["score"] == pytest.approx(3.5 / 7.0)


def test_first_compile_and_far_events_are_not_causes():
    events = [
        {"t": 1.0, "kind": "compile", "step": 10, "first": True},
        {"t": 2.0, "kind": "ckpt_retry", "step": 50},       # outside window
        {"t": 3.0, "kind": "step_end", "step": 10},         # not a cause kind
    ]
    assert correlate_anomaly({"trigger_step": 10}, events, window=10) == []


def test_uncorrelated_anomaly_still_reported(tmp_path):
    _pm(tmp_path, events=[], anomaly={"trigger": "metric",
                                      "metric": "itl_ms",
                                      "trigger_step": 30, "value": 900.0,
                                      "median": 12.0, "z": 50.0})
    findings = diagnose(load_run(tmp_path), tmp_path)
    assert findings[0]["rule"] == "anomaly-uncorrelated"
    assert "no correlated ring event" in findings[0]["message"]


def test_missing_capture_trace_is_called_out(tmp_path):
    _pm(tmp_path, events=[{"t": 1.0, "kind": "ckpt_retry", "step": 30}],
        anomaly={"trigger": "metric", "metric": "step_ms",
                 "trigger_step": 30,
                 "trace_path": str(tmp_path / "anomaly_trace_step30.json")})
    findings = diagnose(load_run(tmp_path), tmp_path)
    assert "MISSING" in findings[0]["message"]


def _lock_pm(tmp_path, cycles, step=None):
    (tmp_path / "postmortem_lock_cycle.json").write_text(json.dumps({
        "reason": "lock_cycle", "written_at": 0.0,
        "last_completed_step": step, "num_events": 2, "cycles": cycles,
        "events": [
            {"kind": "lock_edge", "frm": "pipeline.py:88",
             "to": "pipeline.py:91", "thread": "MainThread"},
            {"kind": "lock_edge", "frm": "pipeline.py:91",
             "to": "pipeline.py:88", "thread": "dla-rollout-generator"}],
        "attr_threads": {}}))


def test_lock_cycle_postmortem_is_an_error_finding(tmp_path):
    _lock_pm(tmp_path,
             [["pipeline.py:88", "pipeline.py:91", "pipeline.py:88"]])
    findings = diagnose(load_run(tmp_path), tmp_path)
    top = findings[0]
    assert top["rule"] == "lock-cycle" and top["severity"] == "error"
    assert ("pipeline.py:88 -> pipeline.py:91 -> pipeline.py:88"
            in top["message"])
    assert top["data"]["edges"]    # the observed edges ride along


def test_lock_cycle_explains_a_watchdog_hang(tmp_path):
    _lock_pm(tmp_path, [["a", "b", "a"]])
    _pm(tmp_path, events=[{"t": 1.0, "kind": "watchdog_hang", "step": 7}],
        name="postmortem_hang.json")
    findings = diagnose(load_run(tmp_path), tmp_path)
    assert findings[0]["rule"] == "lock-cycle"
    assert "watchdog hang at step 7" in findings[0]["message"]


def test_lock_cycle_with_step_is_a_correlatable_cause(tmp_path):
    _lock_pm(tmp_path, [["a", "b", "a"]], step=7)
    _pm(tmp_path, events=[],
        anomaly={"trigger": "metric", "metric": "step_ms",
                 "trigger_step": 7, "value": 900.0, "median": 12.0,
                 "z": 50.0})
    findings = diagnose(load_run(tmp_path), tmp_path)
    corr = [f for f in findings if f["rule"] == "anomaly-correlated"]
    assert corr and "runtime lock-order cycle" in corr[0]["message"]


def test_unattributed_recompile_outranks_attributed(tmp_path):
    _pm(tmp_path, events=[
        {"t": 1.0, "kind": "compile", "step": 3, "fn": "decode",
         "attributed": True, "changed": "x: f32[2] -> f32[4]"},
        {"t": 2.0, "kind": "compile", "step": 9, "fn": "decode",
         "attributed": False},
    ])
    findings = diagnose(load_run(tmp_path), tmp_path)
    rules = [f["rule"] for f in findings]
    assert rules.index("recompile-unattributed") \
        < rules.index("recompile-attributed")
    attributed = next(f for f in findings
                      if f["rule"] == "recompile-attributed")
    assert "f32[2] -> f32[4]" in attributed["message"]


def test_flops_divergence_metric_check(tmp_path):
    (tmp_path / "metrics.prom").write_text(
        "dla_telemetry_xla_train_step_flops_within_tolerance 0.0\n")
    findings = diagnose(load_run(tmp_path), tmp_path)
    assert any(f["rule"] == "metric-flops-divergence" for f in findings)


def test_bench_overhead_rides_along(tmp_path):
    (tmp_path / "bench_introspect.json").write_text(json.dumps(
        {"metrics": {"introspect_overhead_ms_per_step": {
            "vs_baseline_frac": 0.25}}}))
    findings = diagnose(load_run(tmp_path), tmp_path)
    assert any(f["rule"] == "bench-overhead" for f in findings)


def test_unreadable_artifacts_never_fatal(tmp_path):
    (tmp_path / "postmortem_anomaly.json").write_text("{truncated")
    (tmp_path / "anomaly_trace_step5.json").write_text("[oops")
    findings = diagnose(load_run(tmp_path), tmp_path)
    assert sum(f["rule"] == "artifact-unreadable"
               for f in findings) == 2


# ---------------------------------------------------------------------------
# multi-process correlation: a sampler-side wedge explains a
# learner-side anomaly (dla-doctor over N artifact dirs)
# ---------------------------------------------------------------------------

def _fleet_dirs(tmp_path):
    """Two processes' artifact dirs: the learner saw a step-time
    anomaly at step 12 with NO local cause in its ring; the sampler
    process logged an injected fault at rollout 12 (one rollout per
    learner step in the lockstep loop)."""
    learner = tmp_path / "learner"
    sampler = tmp_path / "sampler0"
    learner.mkdir()
    sampler.mkdir()
    (learner / "postmortem_anomaly.json").write_text(json.dumps({
        "reason": "anomaly",
        "anomaly": {"trigger": "metric", "metric": "step_ms",
                    "trigger_step": 12, "value": 900.0, "median": 80.0,
                    "z": 40.0},
        "events": [{"t": 5.0, "kind": "step_end", "step": 12}]}))
    (sampler / "postmortem_fleet.json").write_text(json.dumps({
        "reason": "anomaly",
        "events": [
            {"t": 4.0, "kind": "sampler_fault", "rollout": 12,
             "slot": 1, "fault": "lost"},
            {"t": 4.2, "kind": "sampler_reassigned", "rollout": 12,
             "slot": 1}]}))
    return learner, sampler


def test_cross_process_cause_ranked_first(tmp_path):
    learner, sampler = _fleet_dirs(tmp_path)
    run = load_runs([learner, sampler])
    assert set(run["dirs"]) == {"learner", "sampler0"}
    findings = diagnose(run, learner)
    top = findings[0]
    assert top["rule"] == "anomaly-correlated"
    # desc names the anomaly's process, cause names the sampler's
    assert "[learner]" in top["message"]
    assert "sampler fault" in top["message"]
    assert "in sampler0" in top["message"]
    assert top["data"]["cause"]["kind"] == "sampler_fault"
    assert top["data"]["cause"]["proc"] == "sampler0"
    # the sampler fault (weight 3.6, distance 0) outranks the
    # reassignment it triggered (weight 2.8)
    assert top["data"]["cause"]["score"] == pytest.approx(3.6)


def test_single_dir_load_runs_is_load_run(tmp_path):
    _pm(tmp_path, events=[], anomaly={"trigger": "metric",
                                      "metric": "step_ms",
                                      "trigger_step": 3, "value": 1.0,
                                      "median": 1.0, "z": 0.0})
    solo = load_runs([tmp_path])
    assert set(solo["dirs"]) == {tmp_path.name}
    assert len(solo["postmortems"]) == 1
    # no _proc tag, no key prefixing in the single-dir shape
    assert "_proc" not in solo["postmortems"][0]


def test_cli_accepts_multiple_dirs(tmp_path, capsys):
    learner, sampler = _fleet_dirs(tmp_path)
    rc = main([str(learner), str(sampler), "--format", "json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    validate_report(doc)
    assert doc["summary"]["dirs"] == 2
    assert doc["findings"][0]["rule"] == "anomaly-correlated"
    assert "in sampler0" in doc["findings"][0]["message"]
    # a missing dir anywhere in the list is still a usage error
    assert main([str(learner), str(tmp_path / "nope")]) == 2


# ---------------------------------------------------------------------------
# satellite: the metrics tooling knows the new telemetry names
# ---------------------------------------------------------------------------

def test_new_telemetry_names_are_catalog_and_round_trip():
    """telemetry/xla/* and telemetry/anomaly/* are declared (static
    check passes over their emission sites — scripts/lint.sh enforces
    it) and survive a Prometheus render/parse round trip."""
    from dla_tpu.telemetry import (
        MetricRegistry, is_catalog_name, parse_prometheus_text)
    for name in ("telemetry/xla/recompiles", "telemetry/xla/live_bytes",
                 "telemetry/xla/train_step/flops",
                 "telemetry/xla/decode/roofline_intensity",
                 "telemetry/anomaly/triggers",
                 "telemetry/anomaly/captures"):
        assert is_catalog_name(name), name

    reg = MetricRegistry()
    reg.counter("telemetry/xla/recompiles").inc()
    reg.counter("telemetry/anomaly/triggers").inc()
    reg.gauge("telemetry/xla/train_step/flops").set(1.5e9)
    parsed = parse_prometheus_text(reg.prometheus_text())
    flat = {name for name, _ in parsed}
    assert "dla_telemetry_xla_recompiles_total" in flat
    assert "dla_telemetry_anomaly_triggers_total" in flat
    assert "dla_telemetry_xla_train_step_flops" in flat


def test_metrics_diff_classifies_new_series(tmp_path, capsys):
    """metrics_diff over two Prometheus dumps carrying the new series:
    recompile counters are informational (direction unknown), the bench
    overhead metric regresses when it grows."""
    from tools.metrics_diff import direction, main as mdiff_main
    assert direction("dla_telemetry_xla_recompiles_total") == 0
    assert direction("introspect_overhead_ms_per_step") == -1
    assert direction("telemetry/xla/live_bytes") == 0

    base = tmp_path / "base.prom"
    cand = tmp_path / "cand.prom"
    base.write_text("dla_telemetry_xla_recompiles_total 0\n"
                    "dla_telemetry_anomaly_captures_total 0\n")
    cand.write_text("dla_telemetry_xla_recompiles_total 4\n"
                    "dla_telemetry_anomaly_captures_total 1\n")
    rc = mdiff_main([str(base), str(cand), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    validate_report(doc)
    assert rc == 0                 # moved-but-informational: not a gate
    moved = {f["data"]["metric"] for f in doc["findings"]
             if f["rule"] == "metric-moved"}
    assert "dla_telemetry_xla_recompiles_total" in moved

    # the bench overhead target IS gated: growth = regression
    b2, c2 = tmp_path / "b2.json", tmp_path / "c2.json"
    b2.write_text('{"introspect_overhead_ms_per_step": 1.0}')
    c2.write_text('{"introspect_overhead_ms_per_step": 2.0}')
    assert mdiff_main([str(b2), str(c2), "--format", "json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"][0]["rule"] == "metric-regression"


def test_check_metric_names_accepts_new_emission_sites():
    """The repo-wide static check stays green with the xla_introspect /
    anomaly emission sites in tree (the names ride the CATALOG's
    telemetry/xla/ and telemetry/anomaly/ dynamic prefixes)."""
    from tools.check_metric_names import run
    assert run() == 0
