"""RLHF rollout-update loop smoke tests (reinforce + ppo modes)."""
import json

import numpy as np
import pytest
import yaml

from dla_tpu.data.jsonl import write_jsonl


def _rlhf_cfg(tmp_path, algo="reinforce", steps=6):
    write_jsonl(tmp_path / "prompts.jsonl",
                [{"prompt": f"say something about topic {i}"}
                 for i in range(32)])
    cfg = {
        "experiment_name": f"rlhf_{algo}",
        "seed": 0,
        "model": {
            "policy_model_name_or_path": "tiny",
            "reference_model_name_or_path": "tiny",
            "tokenizer": "byte",
            "max_seq_length": 48,
        },
        "reward_model": {"base_model_name_or_path": "tiny",
                         "tokenizer": "byte", "max_seq_length": 48},
        "ppo": {
            "algo": algo,
            "batch_size": 8,
            "mini_batch_size": 4,
            "epochs": 1,
            "learning_rate": 1e-4,
            "kl_coef": 0.1,
            "target_kl": 6.0,
            "steps": steps,
            "generation_params": {
                "max_new_tokens": 8, "temperature": 0.7, "top_p": 0.9},
        },
        "sampling": {"source": "local",
                     "prompt_path": str(tmp_path / "prompts.jsonl")},
        "logging": {
            "output_dir": str(tmp_path / "ckpt"),
            "log_dir": str(tmp_path / "logs"),
            "log_every_steps": 2,
        },
        "hardware": {"mesh": {"data": 2, "fsdp": 2, "model": 2}},
    }
    p = tmp_path / "rlhf.yaml"
    p.write_text(yaml.safe_dump(cfg))
    return p


def _metrics(tmp_path):
    recs = []
    with open(tmp_path / "logs" / "metrics.jsonl") as fh:
        for line in fh:
            recs.append(json.loads(line))
    return recs


def test_rlhf_reinforce_runs_and_logs(tmp_path):
    from dla_tpu.training.train_rlhf import main
    main(["--config", str(_rlhf_cfg(tmp_path, "reinforce"))])
    recs = _metrics(tmp_path)
    assert recs, "no metrics logged"
    last = recs[-1]
    for key in ("train/loss", "train/kl", "train/reward_mean",
                "train/rm_score_mean", "train/response_len"):
        assert key in last and np.isfinite(last[key]), key
    # fresh identical policy/ref: first-step KL must be near zero
    assert abs(recs[0]["train/kl"]) < 0.5
    assert (tmp_path / "ckpt" / "final").is_dir()


def test_rlhf_ppo_minibatch_mode(tmp_path):
    from dla_tpu.training.train_rlhf import main
    main(["--config", str(_rlhf_cfg(tmp_path, "ppo", steps=4))])
    recs = _metrics(tmp_path)
    assert recs
    assert np.isfinite(recs[-1]["train/loss"])
    assert "train/kl_coef" in recs[-1]


def test_rollout_rows_round_down_logs(capsys):
    """The per-host round-down of ppo.batch_size is a silent size
    degradation unless announced (VERDICT r3)."""
    from dla_tpu.training.train_rlhf import compute_rollout_rows
    assert compute_rollout_rows(64, 1) == 64
    assert compute_rollout_rows(64, 4) == 64
    assert capsys.readouterr().out == ""
    assert compute_rollout_rows(65, 4) == 64
    out = capsys.readouterr().out
    assert "65" in out and "64 rows" in out and "1 dropped" in out


def test_gae_advantages_match_naive_loop():
    """GAE reverse scan == the textbook per-row python recursion, with a
    contiguous action region and terminal bootstrap V := 0."""
    import jax.numpy as jnp
    from dla_tpu.ops.losses import gae_advantages

    rs = np.random.RandomState(0)
    B, T = 3, 10
    gamma, lam = 0.99, 0.9
    rewards = rs.randn(B, T).astype(np.float32)
    values = rs.randn(B, T).astype(np.float32)
    # rows: actions at [2, 8), [0, 10), [5, 6)
    spans = [(2, 8), (0, 10), (5, 6)]
    am = np.zeros((B, T), np.int32)
    for b, (lo, hi) in enumerate(spans):
        am[b, lo:hi] = 1
    rewards = rewards * am

    adv, ret = gae_advantages(jnp.asarray(rewards), jnp.asarray(values),
                              jnp.asarray(am), gamma, lam)

    want_adv = np.zeros((B, T), np.float32)
    for b, (lo, hi) in enumerate(spans):
        a_next = 0.0
        for t in range(hi - 1, lo - 1, -1):
            v_next = values[b, t + 1] if t + 1 < hi else 0.0
            delta = rewards[b, t] + gamma * v_next - values[b, t]
            a_next = delta + gamma * lam * a_next
            want_adv[b, t] = a_next
    np.testing.assert_allclose(np.asarray(adv), want_adv,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ret),
                               want_adv + values * am, rtol=1e-5, atol=1e-5)


def test_rlhf_gae_critic_mode(tmp_path):
    """Per-token critic PPO: runs end-to-end on the mesh, logs finite
    losses, writes a final checkpoint with the nested policy+value tree."""
    from dla_tpu.training.train_rlhf import main
    main(["--config", str(_rlhf_cfg(tmp_path, "gae", steps=4))])
    recs = _metrics(tmp_path)
    assert recs
    last = recs[-1]
    assert np.isfinite(last["train/loss"])
    assert "train/kl_coef" in last
    # fresh identical policy/ref: first-logged KL near zero
    assert abs(recs[0]["train/kl"]) < 0.5
    assert (tmp_path / "ckpt" / "final").is_dir()


def test_rlhf_gae_checkpoint_chains(tmp_path):
    """The non-LoRA gae run's `latest` must load as a plain causal LM
    (phase chaining: checkpoints/rlhf/latest -> next phase/eval)."""
    import jax
    from dla_tpu.training.model_io import load_causal_lm
    from dla_tpu.training.train_rlhf import main

    main(["--config", str(_rlhf_cfg(tmp_path, "gae", steps=2))])
    bundle = load_causal_lm(
        str(tmp_path / "ckpt" / "latest"), {"tokenizer": "byte"},
        jax.random.key(0))
    ids = np.random.RandomState(0).randint(1, 100, (2, 8)).astype(np.int32)
    out = bundle.model.apply(bundle.params, ids)
    assert np.isfinite(np.asarray(out)).all()


def _enable_quant(cfg_path):
    import yaml
    cfg = yaml.safe_load(open(cfg_path))
    cfg["ppo"]["rollout_quantize_weights"] = True
    open(cfg_path, "w").write(yaml.safe_dump(cfg))
    return cfg_path


def test_quantized_rollout_gae_scores_from_quantized_tree(tmp_path,
                                                         monkeypatch):
    """Round-5 verdict item 5: with ppo.rollout_quantize_weights, GAE's
    behavior_logp/behavior_values must come from the SAME int8 tree that
    sampled (previously gae scored from full precision — off-policy
    drift). The spy asserts the score fn receives int8 weights and no
    separate adapters."""
    import jax.numpy as jnp

    import dla_tpu.training.train_rlhf as tr
    seen = {}
    real = tr.make_gae_score_fn

    def spy(*a, **k):
        fn = real(*a, **k)

        def wrapped(policy_params, value_head, ref_params, rm_params,
                    *args, **kw):
            seen["int8"] = policy_params["layers"]["wq"].dtype == jnp.int8
            seen["lora"] = kw.get("lora") is not None
            return fn(policy_params, value_head, ref_params, rm_params,
                      *args, **kw)
        return wrapped

    monkeypatch.setattr(tr, "make_gae_score_fn", spy)
    cfgp = _enable_quant(_rlhf_cfg(tmp_path, "gae", steps=2))
    tr.main(["--config", str(cfgp)])
    assert seen.get("int8") is True, (
        "gae scored from a non-quantized tree under "
        f"rollout_quantize_weights: {seen}")
    assert seen.get("lora") is False
    assert np.isfinite(_metrics(tmp_path)[-1]["train/loss"])


def test_quantized_rollout_reinforce_scores_from_quantized_tree(
        tmp_path, monkeypatch):
    """Same pin for the reinforce/ppo score path (already consistent —
    regression guard)."""
    import jax.numpy as jnp

    import dla_tpu.training.train_rlhf as tr
    seen = {}
    real = tr.make_score_fn

    def spy(*a, **k):
        fn = real(*a, **k)

        def wrapped(policy_params, *args, **kw):
            seen["int8"] = policy_params["layers"]["wq"].dtype == jnp.int8
            return fn(policy_params, *args, **kw)
        return wrapped

    monkeypatch.setattr(tr, "make_score_fn", spy)
    cfgp = _enable_quant(_rlhf_cfg(tmp_path, "reinforce"))
    tr.main(["--config", str(cfgp)])
    assert seen.get("int8") is True
    assert np.isfinite(_metrics(tmp_path)[-1]["train/loss"])


def test_local_rollout_shape_host_and_group_edge_cases(capsys):
    """The per-host / per-group factoring behind the serving rollout
    backend: rows round down per host exactly like compute_rollout_rows,
    and G must divide the per-host rollout batch."""
    from dla_tpu.training.train_rlhf import compute_local_rollout_shape
    # single host, G=1: identity
    assert compute_local_rollout_shape(64, 1, 1) == (64, 64, 64)
    # 4 hosts: 16 rows each, G=8 -> 2 unique prompts per host
    assert compute_local_rollout_shape(64, 4, 8) == (64, 16, 2)
    capsys.readouterr()
    # 65 rounds down to 64 (announced, same as compute_rollout_rows)
    assert compute_local_rollout_shape(65, 4, 1) == (64, 16, 16)
    assert "dropped" in capsys.readouterr().out
    # G that doesn't divide the local batch is a config error
    with pytest.raises(ValueError, match="samples_per_prompt"):
        compute_local_rollout_shape(64, 4, 3)


def test_rlhf_fleet_chaos_equals_planned_e2e(tmp_path, capsys):
    """The fleet chaos acceptance, end to end through train_rlhf: an
    async sampler-fleet run (N=2) that loses member 1 mid-run via a
    ``sampler=`` fault plan produces the SAME loss trajectory and a
    bit-identical final checkpoint as a planned N=1 run, with the
    learner's train_step compiled exactly once in both."""
    import yaml as _yaml

    from dla_tpu.training.train_rlhf import main

    def run(tag, samplers, fault_plan):
        root = tmp_path / tag
        root.mkdir()
        cfgp = _rlhf_cfg(root, "reinforce", steps=2)
        cfg = _yaml.safe_load(cfgp.read_text())
        cfg["logging"]["log_every_steps"] = 1
        cfg["ppo"]["rollout"] = {
            "backend": "serving", "mode": "async",
            "max_staleness_updates": 2,
            "serving": {"page_size": 4, "fault_plan": fault_plan},
            "fleet": {"samplers": samplers, "lease_ttl_s": 0.5},
        }
        cfgp.write_text(_yaml.safe_dump(cfg))
        main(["--config", str(cfgp)])
        assert "train_step_compiles=1" in capsys.readouterr().out
        recs = []
        with open(root / "logs" / "metrics.jsonl") as fh:
            for line in fh:
                recs.append(json.loads(line))
        return root, recs

    chaos_root, chaos_recs = run(
        "chaos", 2, "sampler=1:rollout_step=1:lost")
    plan_root, plan_recs = run("planned", 1, "")

    assert len(chaos_recs) == len(plan_recs) == 2
    for cr, pr in zip(chaos_recs, plan_recs):
        assert cr["train/loss"] == pr["train/loss"]
        assert cr["train/reward_mean"] == pr["train/reward_mean"]
    c_final = chaos_root / "ckpt" / "final"
    p_final = plan_root / "ckpt" / "final"
    c_files = sorted(f.name for f in c_final.glob("*.npy"))
    assert c_files == sorted(f.name for f in p_final.glob("*.npy"))
    assert c_files, "final checkpoint wrote no arrays"
    for name in c_files:
        assert np.array_equal(np.load(c_final / name),
                              np.load(p_final / name)), name


def test_rlhf_serving_rollout_backend_e2e(tmp_path):
    """End-to-end smoke: the full RLHF loop with ppo.rollout.backend:
    serving — rollouts come from the serving engine (sync mode, refit
    each step) and the metrics surface stays intact."""
    import yaml as _yaml

    from dla_tpu.training.train_rlhf import main
    cfgp = _rlhf_cfg(tmp_path, "reinforce", steps=2)
    cfg = _yaml.safe_load(cfgp.read_text())
    cfg["ppo"]["rollout"] = {"backend": "serving", "mode": "sync",
                             "serving": {"page_size": 4}}
    cfgp.write_text(_yaml.safe_dump(cfg))
    main(["--config", str(cfgp)])
    recs = _metrics(tmp_path)
    assert recs and np.isfinite(recs[-1]["train/loss"])
    assert recs[-1]["train/response_len"] > 0
