"""RLHF rollout-update loop smoke tests (reinforce + ppo modes)."""
import json

import numpy as np
import pytest
import yaml

from dla_tpu.data.jsonl import write_jsonl


def _rlhf_cfg(tmp_path, algo="reinforce", steps=6):
    write_jsonl(tmp_path / "prompts.jsonl",
                [{"prompt": f"say something about topic {i}"}
                 for i in range(32)])
    cfg = {
        "experiment_name": f"rlhf_{algo}",
        "seed": 0,
        "model": {
            "policy_model_name_or_path": "tiny",
            "reference_model_name_or_path": "tiny",
            "tokenizer": "byte",
            "max_seq_length": 48,
        },
        "reward_model": {"base_model_name_or_path": "tiny",
                         "tokenizer": "byte", "max_seq_length": 48},
        "ppo": {
            "algo": algo,
            "batch_size": 8,
            "mini_batch_size": 4,
            "epochs": 1,
            "learning_rate": 1e-4,
            "kl_coef": 0.1,
            "target_kl": 6.0,
            "steps": steps,
            "generation_params": {
                "max_new_tokens": 8, "temperature": 0.7, "top_p": 0.9},
        },
        "sampling": {"source": "local",
                     "prompt_path": str(tmp_path / "prompts.jsonl")},
        "logging": {
            "output_dir": str(tmp_path / "ckpt"),
            "log_dir": str(tmp_path / "logs"),
            "log_every_steps": 2,
        },
        "hardware": {"mesh": {"data": 2, "fsdp": 2, "model": 2}},
    }
    p = tmp_path / "rlhf.yaml"
    p.write_text(yaml.safe_dump(cfg))
    return p


def _metrics(tmp_path):
    recs = []
    with open(tmp_path / "logs" / "metrics.jsonl") as fh:
        for line in fh:
            recs.append(json.loads(line))
    return recs


def test_rlhf_reinforce_runs_and_logs(tmp_path):
    from dla_tpu.training.train_rlhf import main
    main(["--config", str(_rlhf_cfg(tmp_path, "reinforce"))])
    recs = _metrics(tmp_path)
    assert recs, "no metrics logged"
    last = recs[-1]
    for key in ("train/loss", "train/kl", "train/reward_mean",
                "train/rm_score_mean", "train/response_len"):
        assert key in last and np.isfinite(last[key]), key
    # fresh identical policy/ref: first-step KL must be near zero
    assert abs(recs[0]["train/kl"]) < 0.5
    assert (tmp_path / "ckpt" / "final").is_dir()


def test_rlhf_ppo_minibatch_mode(tmp_path):
    from dla_tpu.training.train_rlhf import main
    main(["--config", str(_rlhf_cfg(tmp_path, "ppo", steps=4))])
    recs = _metrics(tmp_path)
    assert recs
    assert np.isfinite(recs[-1]["train/loss"])
    assert "train/kl_coef" in recs[-1]
