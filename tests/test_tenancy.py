"""Multi-tenant LoRA serving tests (dla_tpu/serving/tenancy): the
acceptance pins for the adapter registry + batched multi-adapter
decode + tenant policy plane.

The load-bearing guarantees: N=8 tenants' heterogeneous adapters batch
into ONE decode compile and each tenant's tokens are identical (greedy
AND seeded-sampled, logprobs tight) to a dedicated merged-weights
engine; hot swaps and eviction-recompute and supervisor replay all
preserve that parity; a noisy tenant exhausting its quota sheds only
its own requests; prefix-cache pages never alias across tenants; the
AdapterStore's spill/reload cycle is bit-exact and its refcount
protocol fails loudly on misuse."""
import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.generation.engine import GenerationConfig
from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import Transformer
from dla_tpu.serving import (
    RequestState,
    SamplingParams,
    ServingConfig,
    ServingEngine,
    Supervisor,
    SupervisorConfig,
)
from dla_tpu.serving.tenancy import (
    AdapterPoolConfig,
    AdapterStore,
    export_adapter_tree,
    load_adapter_tree,
)

RANK = 4
ALPHA = 8.0
N_TENANTS = 8
MAX_NEW = 4
CHUNK = 8


@pytest.fixture(scope="module")
def model_and_params():
    cfg = dataclasses.replace(get_model_config("tiny"),
                              lora_r=RANK, lora_alpha=ALPHA)
    model = Transformer(cfg)
    return model, model.init(jax.random.key(7))


@pytest.fixture(scope="module")
def adapters(model_and_params):
    """N distinct adapter trees. init_lora zeros the B factors (an
    identity delta), so BOTH factors are randomized — every tenant must
    decode differently from the base weights and from each other."""
    model, _ = model_and_params
    out = {}
    for i in range(N_TENANTS):
        key = jax.random.key(1000 + i)
        tree = model.init_lora(key)
        layers = {}
        for name, leaf in tree["layers"].items():
            key, sub = jax.random.split(key)
            layers[name] = 0.1 * jax.random.normal(
                sub, leaf.shape, jnp.float32)
        out[f"tenant{i}"] = {"layers": layers}
    return out


def _gen(**kw):
    base = dict(max_new_tokens=MAX_NEW, do_sample=False, eos_token_id=-1,
                pad_token_id=0)
    base.update(kw)
    return GenerationConfig(**base)


def _cfg(n=N_TENANTS, tenancy_extra=None, **over):
    tenancy = {"adapter_pool": {"max_adapters": n, "max_rank": RANK}}
    tenancy.update(tenancy_extra or {})
    base = dict(page_size=4, num_pages=64, num_slots=4, max_model_len=32,
                max_prefill_batch=2, prefill_chunk=CHUNK, tenancy=tenancy)
    base.update(over)
    return ServingConfig(**base)


def _prompts(n, seed, length=6):
    rs = np.random.RandomState(seed)
    return [list(rs.randint(3, 500, (length,))) for _ in range(n)]


def _drain(eng):
    results = eng.run_until_drained(max_steps=2000)
    eng.scheduler.assert_consistent()
    return results


# ---------------------------------------------------------------------------
# THE parity pin: 8 tenants batched == 8 dedicated merged-weight engines
# ---------------------------------------------------------------------------

def test_eight_tenant_batched_parity_greedy_and_seeded(model_and_params,
                                                       adapters):
    """Every tenant's greedy AND seeded-sampled tokens from the ONE
    batched multi-adapter engine equal a merged-weights engine serving
    that tenant alone, with logprobs tight — and the batched engine's
    decode compiled exactly once across the whole 8-tenant mix."""
    model, params = model_and_params
    tenants = sorted(adapters)
    assert len(tenants) == N_TENANTS >= 8
    prompts = dict(zip(tenants, _prompts(N_TENANTS, seed=3)))
    samp = {t: SamplingParams(temperature=0.9, top_p=0.9, top_k=8,
                              seed=100 + i, do_sample=True)
            for i, t in enumerate(tenants)}

    eng = ServingEngine(model, params, _gen(), _cfg())
    for t in tenants:
        eng.publish_adapter(t, adapters[t])
    rids = {}
    for t in tenants:                        # round-robin: mixes tenants
        rids[(t, "greedy")] = eng.submit(prompts[t], MAX_NEW, tenant=t)
    for t in tenants:
        rids[(t, "seeded")] = eng.submit(prompts[t], MAX_NEW, tenant=t,
                                         sampling=samp[t])
    results = _drain(eng)
    assert eng.decode_compiles == 1, (
        "heterogeneous tenant mix retraced the decode step")
    assert eng.cache.allocator.used_count == 0

    # reference arm: ONE engine serially re-published with each
    # tenant's merged weights (publish_params keeps its compile pinned)
    ref = ServingEngine(model, model.merge_lora(params, adapters[
        tenants[0]]), _gen(), ServingConfig(
            page_size=4, num_pages=64, num_slots=4, max_model_len=32,
            max_prefill_batch=2, prefill_chunk=CHUNK))
    for t in tenants:
        ref.publish_params(model.merge_lora(params, adapters[t]))
        rg = ref.submit(prompts[t], MAX_NEW)
        rs_ = ref.submit(prompts[t], MAX_NEW, sampling=samp[t])
        out = _drain(ref)
        for kind, rid in (("greedy", rg), ("seeded", rs_)):
            got = results[rids[(t, kind)]]
            want = out[rid]
            assert got.generated == want.generated, (
                f"{t} {kind} diverged from merged-weights engine")
            np.testing.assert_allclose(
                got.generated_logprobs, want.generated_logprobs,
                atol=5e-4, rtol=0, err_msg=f"{t} {kind} logprobs")
    # distinct adapters actually decode distinctly
    greedy_streams = {tuple(results[rids[(t, "greedy")]].generated)
                      for t in tenants}
    assert len(greedy_streams) > 1


def test_hot_swap_changes_output_without_recompile(model_and_params,
                                                   adapters):
    """publish_adapter on a RESIDENT tenant rewrites its pool row in
    place: the next request decodes under the new factors, the compile
    counters never move, and no other tenant is disturbed."""
    model, params = model_and_params
    ta, tb = "tenant0", "tenant1"
    prompt = _prompts(1, seed=9)[0]
    eng = ServingEngine(model, params, _gen(), _cfg(n=2))
    eng.publish_adapter(ta, adapters[ta])
    eng.publish_adapter(tb, adapters[tb])
    r1 = eng.submit(prompt, MAX_NEW, tenant=ta)
    rb1 = eng.submit(prompt, MAX_NEW, tenant=tb)
    out1 = _drain(eng)

    # hot-swap tenant a to a DIFFERENT adapter tree (tenant2's factors)
    eng.publish_adapter(ta, adapters["tenant2"])
    r2 = eng.submit(prompt, MAX_NEW, tenant=ta)
    rb2 = eng.submit(prompt, MAX_NEW, tenant=tb)
    out2 = _drain(eng)
    assert eng.decode_compiles == 1
    assert eng.adapter_store.publishes == 3

    merged = ServingEngine(model, model.merge_lora(
        params, adapters["tenant2"]), _gen(), ServingConfig(
            page_size=4, num_pages=64, num_slots=4, max_model_len=32,
            max_prefill_batch=2, prefill_chunk=CHUNK))
    rid = merged.submit(prompt, MAX_NEW)
    want = _drain(merged)[rid]
    assert out2[r2].generated == want.generated
    assert out2[r2].generated != out1[r1].generated  # swap took effect
    assert out2[rb2].generated == out1[rb1].generated  # b undisturbed


# ---------------------------------------------------------------------------
# tenant quota isolation
# ---------------------------------------------------------------------------

def test_noisy_tenant_sheds_only_its_own_requests(model_and_params,
                                                  adapters):
    """One tenant floods a near-empty token bucket: every shed lands on
    the noisy tenant (at="tenant_quota"), every other tenant's requests
    finish, and their shed counters stay at zero."""
    model, params = model_and_params
    tenants = ["tenant0", "tenant1", "tenant2"]
    noisy = tenants[0]
    eng = ServingEngine(model, params, _gen(), _cfg(
        n=3, tenancy_extra={
            "quotas": {noisy: {"rate": 1e-6, "burst": 1.0}}}))
    for t in tenants:
        eng.publish_adapter(t, adapters[t])
    prompts = _prompts(6, seed=21)
    flood = [eng.submit(p, MAX_NEW, tenant=noisy) for p in prompts]
    quiet = [eng.submit(p, MAX_NEW, tenant=t)
             for t in tenants[1:] for p in prompts[:2]]
    results = _drain(eng)

    shed = [r for r in flood if results[r].state is RequestState.SHED]
    assert len(shed) == len(flood) - 1     # burst=1 admits exactly one
    assert all(results[r].finish_reason == "shed" for r in shed)
    for r in quiet:
        assert results[r].state is RequestState.FINISHED
    snap = eng.metrics.registry.snapshot()
    assert snap[f"serving/tenant/{noisy}/requests_shed"] == len(shed)
    for t in tenants[1:]:
        assert snap[f"serving/tenant/{t}/requests_shed"] == 0.0
        assert snap[f"serving/tenant/{t}/requests_finished"] == 2.0
        assert snap[f"serving/tenant/{t}/tokens_generated"] \
            == 2.0 * MAX_NEW


# ---------------------------------------------------------------------------
# parity across eviction-recompute and supervisor replay
# ---------------------------------------------------------------------------

def test_eviction_recompute_keeps_tenant_parity(model_and_params,
                                                adapters):
    """A page pool sized to force mid-decode preemption: the evicted
    tenant request re-prefills (releasing and re-acquiring its adapter
    pin) and still lands on the merged-weights reference tokens."""
    model, params = model_and_params
    tenants = ["tenant0", "tenant1"]
    prompts = dict(zip(tenants, _prompts(2, seed=11, length=4)))
    new = 5
    # capacity 7 pages (page 0 reserved): both 4-token prompts admit
    # but cannot both grow to 9 tokens -> someone is preempted
    eng = ServingEngine(model, params, _gen(max_new_tokens=new), _cfg(
        n=2, page_size=2, num_pages=8, num_slots=2, max_model_len=12,
        prefill_chunk=4))
    for t in tenants:
        eng.publish_adapter(t, adapters[t])
    rids = {t: eng.submit(prompts[t], new, tenant=t) for t in tenants}
    results = _drain(eng)
    assert eng.metrics.preemptions.value >= 1, (
        "config was meant to force at least one preemption")
    assert eng.cache.allocator.used_count == 0

    ref = ServingEngine(model, model.merge_lora(params, adapters[
        tenants[0]]), _gen(max_new_tokens=new), ServingConfig(
            page_size=2, num_pages=32, num_slots=2, max_model_len=12,
            max_prefill_batch=2, prefill_chunk=4))
    for t in tenants:
        ref.publish_params(model.merge_lora(params, adapters[t]))
        rid = ref.submit(prompts[t], new)
        want = _drain(ref)[rid]
        got = results[rids[t]]
        assert got.generated == want.generated, (
            f"{t} eviction recompute diverged "
            f"(evictions={got.evictions})")


def test_supervisor_replay_rebinds_tenants(model_and_params, adapters):
    """A mid-run device error: the Supervisor rebuilds the engine (the
    factory republishes every adapter), replays the journal with each
    request's tenant, and the outputs stay identical to a fault-free
    multi-tenant run. The adapter-pool counters stay monotone across
    the rebuild."""
    model, params = model_and_params
    tenants = ["tenant0", "tenant1"]
    prompts = _prompts(4, seed=31)
    subs = [(prompts[i], tenants[i % 2]) for i in range(4)]

    def build(fault_plan=None):
        eng = ServingEngine(model, params, _gen(), _cfg(
            n=2, num_slots=2, fault_plan=fault_plan))
        for t in tenants:
            eng.publish_adapter(t, adapters[t])
        return eng

    base_eng = build()
    base_rids = [base_eng.submit(p, MAX_NEW, tenant=t) for p, t in subs]
    base = base_eng.run_until_drained(max_steps=2000)
    baseline = [list(base[r].generated) for r in base_rids]
    base_eng.close()

    engines = []

    def factory():
        eng = build(fault_plan="engine_step=3:device_error")
        engines.append(eng)
        return eng

    sup = Supervisor(factory, SupervisorConfig(
        watchdog_timeout_s=0.05, watchdog_poll_s=0.01, max_restarts=2))
    rids = [sup.submit(p, MAX_NEW, tenant=t) for p, t in subs]
    results = sup.run(max_steps=2000)
    sup.close()

    assert sup.restarts == 1 and not sup.tripped
    for i, rid in enumerate(rids):
        assert results[rid].state is RequestState.FINISHED
        assert list(results[rid].generated) == baseline[i], (
            f"request {i} diverged across supervisor replay")
    assert [e.decode_compiles for e in engines] == [1] * len(engines)
    # counters carried: gen-1's publishes fold into gen-2's registry
    final = engines[-1].metrics
    assert final.adapter_publishes.value == 2 * len(tenants)


def test_restore_unknown_tenant_fails_loudly(model_and_params):
    """Replay into a rebuilt engine whose factory did NOT republish the
    adapter must raise, never silently decode on base weights."""
    model, params = model_and_params
    eng = ServingEngine(model, params, _gen(), _cfg(n=2))
    with pytest.raises(ValueError, match="publish_adapter first"):
        eng.restore([5, 6, 7], MAX_NEW, generated=[], arrival_time=0.0,
                    tenant="tenant0")


# ---------------------------------------------------------------------------
# prefix-cache namespace isolation
# ---------------------------------------------------------------------------

def test_prefix_cache_never_aliases_across_tenants(model_and_params,
                                                   adapters):
    """The same prompt tokens under two tenants: each tenant's pages
    register under its own namespace, so the other tenant (and the base
    namespace) see a cold cache — KV computed under adapter A must
    never serve adapter B."""
    model, params = model_and_params
    eng = ServingEngine(model, params, _gen(), _cfg(
        n=2, prefix_cache=True))
    for t in ("tenant0", "tenant1"):
        eng.publish_adapter(t, adapters[t])
    prompt = _prompts(1, seed=41, length=2 * CHUNK)[0]
    eng.submit(prompt, MAX_NEW, tenant="tenant0")
    _drain(eng)
    pc = eng.prefix_cache
    assert pc.peek(prompt, CHUNK, namespace="tenant0") >= CHUNK
    assert pc.peek(prompt, CHUNK, namespace="tenant1") == 0
    assert pc.peek(prompt, CHUNK, namespace=None) == 0
    # and the reverse: tenant1 registers its own copy, tenant0's stays
    eng.submit(prompt, MAX_NEW, tenant="tenant1")
    _drain(eng)
    assert pc.peek(prompt, CHUNK, namespace="tenant1") >= CHUNK
    assert pc.peek(prompt, CHUNK, namespace="tenant0") >= CHUNK


# ---------------------------------------------------------------------------
# AdapterStore unit behavior (no engine)
# ---------------------------------------------------------------------------

def _store(model, max_adapters=2, max_rank=RANK):
    return AdapterStore(model, AdapterPoolConfig(
        max_adapters=max_adapters, max_rank=max_rank))


def test_store_lru_spill_and_reload_bit_identical(model_and_params,
                                                  adapters):
    model, _ = model_and_params
    st = _store(model, max_adapters=2)
    for t in ("tenant0", "tenant1", "tenant2"):
        st.publish(t, adapters[t])
    assert st.tenants == ["tenant0", "tenant1", "tenant2"]
    assert st.publishes == 3 and st.resident_count == 0

    i0 = st.acquire("tenant0")
    i1 = st.acquire("tenant1")
    assert i0 != i1 and 0 not in (i0, i1)   # row 0 = base identity
    key = f"{st.targets[0]}_lora_a"
    row0_before = np.asarray(st.pools[key][i0])
    assert np.any(row0_before)              # factors actually landed

    # both rows pinned: residency for a third tenant must fail loudly
    with pytest.raises(RuntimeError, match="adapter pool exhausted"):
        st.acquire("tenant2")

    st.release("tenant0")                   # refcount 0 -> spillable
    i2 = st.acquire("tenant2")
    assert i2 == i0                         # LRU row reused
    assert st.spills == 1 and not st.resident("tenant0")
    assert st.has("tenant0")                # host copy stays

    st.release("tenant2")
    i0b = st.acquire("tenant0")             # reload from host copy
    np.testing.assert_array_equal(
        np.asarray(st.pools[key][i0b]), row0_before)
    assert st.loads == 4                    # 3 first loads + 1 reload


def test_store_refcount_underflow_and_unknown_tenant(model_and_params,
                                                     adapters):
    model, _ = model_and_params
    st = _store(model)
    st.publish("tenant0", adapters["tenant0"])
    with pytest.raises(RuntimeError, match="release underflow"):
        st.release("tenant0")
    with pytest.raises(KeyError, match="publish_adapter first"):
        st.ensure_resident("nobody")
    with pytest.raises(ValueError, match="invalid tenant id"):
        st.publish("../etc", adapters["tenant0"])


def test_store_rank_padding_and_validation(model_and_params, adapters):
    model, _ = model_and_params
    st = _store(model, max_rank=RANK + 2)
    st.publish("tenant0", adapters["tenant0"])   # r=4 into max_rank=6
    idx = st.acquire("tenant0")
    a = np.asarray(st.pools[f"{st.targets[0]}_lora_a"][idx])
    assert a.shape[-1] == RANK + 2
    assert np.all(a[..., RANK:] == 0.0)          # zero pad: exact math

    st2 = _store(model, max_rank=RANK - 2)
    with pytest.raises(ValueError, match="exceeds the pool's max_rank"):
        st2.publish("tenant0", adapters["tenant0"])

    st3 = _store(model)
    with pytest.raises(ValueError, match="publish_params"):
        # a full param tree is NOT an adapter tree — the error routes
        # the caller to the right publish
        st3.publish("tenant0", {"layers": {"bogus": np.zeros((2, 2))}})


def test_publish_params_routes_adapter_trees_to_publish_adapter(
        model_and_params, adapters):
    """Satellite pin: a would-be full-tree republish with an
    adapter-only tree points at publish_adapter, and vice versa."""
    model, params = model_and_params
    eng = ServingEngine(model, params, _gen(), _cfg(n=2))
    with pytest.raises(ValueError, match="publish_adapter"):
        eng.publish_params(adapters["tenant0"])
    assert "publish_adapter" in (ServingEngine.publish_params.__doc__
                                 or "")
    plain = ServingEngine(model, params, _gen(), ServingConfig(
        page_size=4, num_pages=64, num_slots=2, max_model_len=32,
        prefill_chunk=CHUNK))
    with pytest.raises(RuntimeError, match="cfg.tenancy"):
        plain.publish_adapter("tenant0", adapters["tenant0"])
    with pytest.raises(ValueError, match="cfg.tenancy"):
        plain.submit([5, 6, 7], MAX_NEW, tenant="tenant0")
    with pytest.raises(ValueError, match="unknown tenant"):
        eng.submit([5, 6, 7], MAX_NEW, tenant="never-published")


def test_tenancy_requires_chunked_prefill(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match="prefill_chunk"):
        ServingEngine(model, params, _gen(), ServingConfig(
            page_size=4, num_pages=64, num_slots=2, max_model_len=32,
            prefill_chunk=0,
            tenancy={"adapter_pool": {"max_adapters": 2,
                                      "max_rank": RANK}}))


# ---------------------------------------------------------------------------
# servable export round-trip
# ---------------------------------------------------------------------------

def test_export_load_publish_roundtrip(model_and_params, adapters,
                                       tmp_path):
    """export_adapter_tree -> load_adapter_tree -> publish produces a
    pool row bit-identical to publishing the in-memory tree directly
    (the finished-RLHF-run -> serving path, no checkpoint re-derive)."""
    model, _ = model_and_params
    tree = adapters["tenant0"]
    out = export_adapter_tree(
        str(tmp_path / "servable"), tree,
        targets=tuple(model.cfg.lora_targets), rank=RANK, alpha=ALPHA,
        num_layers=model.cfg.num_layers, tenant="tenant0")
    loaded, manifest = load_adapter_tree(out)
    assert manifest["format"] == "adapter_store/v1"
    assert manifest["rank"] == RANK and manifest["alpha"] == ALPHA
    assert manifest["tenant"] == "tenant0"

    st_direct, st_loaded = _store(model), _store(model)
    st_direct.publish("tenant0", tree)
    st_loaded.publish("tenant0", loaded, alpha=manifest["alpha"],
                      rank=manifest["rank"])
    ia = st_direct.acquire("tenant0")
    ib = st_loaded.acquire("tenant0")
    for key in st_direct.pools:
        np.testing.assert_array_equal(
            np.asarray(st_direct.pools[key][ia]),
            np.asarray(st_loaded.pools[key][ib]), err_msg=key)

    bad = tmp_path / "notservable"
    bad.mkdir()
    (bad / "manifest.json").write_text('{"format": "something/v9"}')
    with pytest.raises(ValueError, match="adapter_store/v1"):
        load_adapter_tree(str(bad))
