"""bench.py parent-orchestrator logic: the descent ladder must treat
child crashes/OOMs as retryable, error-bearing JSON lines as failures
(regression: a child backstop once emitted a value-0.0 line on HBM OOM,
which the parent accepted as a measurement and froze the ladder on the
first rung), and timeouts as tunnel wedges that end accel attempts."""
import json
import subprocess
import types

import bench


def test_extract_json_line_picks_metric_line():
    text = "\n".join([
        "[bench] noise",
        '{"not_metric": 1}',
        '{"metric": "sft_tokens_per_sec_per_chip", "value": 5.0}',
    ])
    got = bench._extract_json_line(text)
    assert got and got["value"] == 5.0


def test_extract_json_line_none_on_garbage():
    assert bench._extract_json_line("no json here\n{broken") is None


def _fake_run(stdout="", returncode=0, raise_timeout=False):
    def run(cmd, **kw):
        if raise_timeout:
            raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 1),
                                            output=stdout, stderr="")
        return types.SimpleNamespace(stdout=stdout, stderr="",
                                     returncode=returncode)
    return run


def test_relay_child_ok(monkeypatch):
    line = json.dumps({"metric": "m", "value": 3.0})
    monkeypatch.setattr(bench.subprocess, "run", _fake_run(stdout=line))
    monkeypatch.setattr(bench, "_child_env", lambda mode: {})
    result, status = bench._relay_child("accel", 10)
    assert status == "ok" and result["value"] == 3.0


def test_relay_child_error_line_is_failure(monkeypatch):
    """A JSON line carrying an error field is NOT a measurement — the
    ladder must retry a smaller config instead of recording 0.0."""
    line = json.dumps({"metric": "m", "value": 0.0,
                       "error": "RESOURCE_EXHAUSTED: hbm"})
    monkeypatch.setattr(bench.subprocess, "run", _fake_run(stdout=line))
    monkeypatch.setattr(bench, "_child_env", lambda mode: {})
    result, status = bench._relay_child("accel", 10)
    assert result is None and status == "failed"


def test_relay_child_crash_is_failure(monkeypatch):
    monkeypatch.setattr(bench.subprocess, "run",
                        _fake_run(stdout="", returncode=2))
    monkeypatch.setattr(bench, "_child_env", lambda mode: {})
    result, status = bench._relay_child("accel", 10)
    assert result is None and status == "failed"


def test_relay_child_no_backend_rc1(monkeypatch):
    monkeypatch.setattr(bench.subprocess, "run",
                        _fake_run(stdout="", returncode=1))
    monkeypatch.setattr(bench, "_child_env", lambda mode: {})
    result, status = bench._relay_child("accel", 10)
    assert result is None and status == "no_backend"


def test_relay_child_timeout_is_wedge(monkeypatch):
    monkeypatch.setattr(bench.subprocess, "run",
                        _fake_run(raise_timeout=True))
    monkeypatch.setattr(bench, "_child_env", lambda mode: {})
    result, status = bench._relay_child("accel", 10)
    assert result is None and status == "timeout"


def test_sweep_variants_bind_to_run_variant():
    """Every variant BASELINE.md points at as a reproduction command must
    bind cleanly to run_variant's signature (a typo'd kwarg would only
    surface on the TPU, mid-measurement)."""
    import importlib.util
    import inspect
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "sweep_bench.py")
    spec = importlib.util.spec_from_file_location("sweep_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    sig = inspect.signature(mod.run_variant)
    assert mod.VARIANTS, "sweep has no variants"
    for name, kw in mod.VARIANTS.items():
        sig.bind(name, **kw)  # raises TypeError on a bad kwarg
    # the exact reproduction commands BASELINE.md cites must resolve
    for cited in ("kv4_micro8_packed", "kv4_seq32k_micro1",
                  "kv4_micro8_b256", "hd128_kv4_micro8_bf16m"):
        assert cited in mod.VARIANTS, f"BASELINE.md cites {cited}"
