"""bench.py parent-orchestrator logic: the descent ladder must treat
child crashes/OOMs as retryable, error-bearing JSON lines as failures
(regression: a child backstop once emitted a value-0.0 line on HBM OOM,
which the parent accepted as a measurement and froze the ladder on the
first rung), and timeouts as tunnel wedges that end accel attempts."""
import json
import subprocess
import types

import bench


def test_extract_json_line_picks_metric_line():
    text = "\n".join([
        "[bench] noise",
        '{"not_metric": 1}',
        '{"metric": "sft_tokens_per_sec_per_chip", "value": 5.0}',
    ])
    got = bench._extract_json_line(text)
    assert got and got["value"] == 5.0


def test_extract_json_line_none_on_garbage():
    assert bench._extract_json_line("no json here\n{broken") is None


def _fake_run(stdout="", returncode=0, raise_timeout=False):
    def run(cmd, **kw):
        if raise_timeout:
            raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 1),
                                            output=stdout, stderr="")
        return types.SimpleNamespace(stdout=stdout, stderr="",
                                     returncode=returncode)
    return run


def test_relay_child_ok(monkeypatch):
    line = json.dumps({"metric": "m", "value": 3.0})
    monkeypatch.setattr(bench.subprocess, "run", _fake_run(stdout=line))
    monkeypatch.setattr(bench, "_child_env", lambda mode: {})
    result, status = bench._relay_child("accel", 10)
    assert status == "ok" and result["value"] == 3.0


def test_relay_child_error_line_is_failure(monkeypatch):
    """A JSON line carrying an error field is NOT a measurement — the
    ladder must retry a smaller config instead of recording 0.0."""
    line = json.dumps({"metric": "m", "value": 0.0,
                       "error": "RESOURCE_EXHAUSTED: hbm"})
    monkeypatch.setattr(bench.subprocess, "run", _fake_run(stdout=line))
    monkeypatch.setattr(bench, "_child_env", lambda mode: {})
    result, status = bench._relay_child("accel", 10)
    assert result is None and status == "failed"


def test_relay_child_crash_is_failure(monkeypatch):
    monkeypatch.setattr(bench.subprocess, "run",
                        _fake_run(stdout="", returncode=2))
    monkeypatch.setattr(bench, "_child_env", lambda mode: {})
    result, status = bench._relay_child("accel", 10)
    assert result is None and status == "failed"


def test_relay_child_no_backend_rc1(monkeypatch):
    monkeypatch.setattr(bench.subprocess, "run",
                        _fake_run(stdout="", returncode=1))
    monkeypatch.setattr(bench, "_child_env", lambda mode: {})
    result, status = bench._relay_child("accel", 10)
    assert result is None and status == "no_backend"


def test_relay_child_timeout_is_wedge(monkeypatch):
    monkeypatch.setattr(bench.subprocess, "run",
                        _fake_run(raise_timeout=True))
    monkeypatch.setattr(bench, "_child_env", lambda mode: {})
    result, status = bench._relay_child("accel", 10)
    assert result is None and status == "timeout"


def _run_parent(monkeypatch, capsys, script):
    """Drive bench.main() with a scripted _relay_child; returns
    (modes-called list, emitted JSON line)."""
    calls = []

    def fake_relay(mode, timeout_s):
        calls.append(mode)
        assert script, f"unexpected extra child call: {mode}"
        want, ret = script.pop(0)
        assert want == mode, f"expected {want} child, got {mode}"
        return ret

    monkeypatch.setattr(bench, "_relay_child", fake_relay)
    monkeypatch.delenv("DLA_BENCH_PLATFORM", raising=False)
    monkeypatch.delenv("DLA_BENCH_MICRO", raising=False)
    assert bench.main() == 0
    out = capsys.readouterr().out
    return calls, bench._extract_json_line(out)


def test_probe_timeout_skips_accel_ladder(monkeypatch, capsys):
    """A wedged tunnel (probe timeout) must cost one probe budget, not
    len(ladder) * accel budget: no accel child may run."""
    cpu_line = ({"metric": "sft_tokens_per_sec_per_chip", "value": 1.0,
                 "detail": {"platform": "cpu"}}, "ok")
    calls, got = _run_parent(monkeypatch, capsys, [
        ("probe", (None, "timeout")), ("cpu", cpu_line)])
    assert calls == ["probe", "cpu"]
    assert got["value"] == 1.0


def test_probe_cpu_only_skips_accel_ladder(monkeypatch, capsys):
    """Probe succeeding on CPU means no accelerator exists — measuring
    the accel config on host CPU would burn the window for nothing."""
    probe = ({"metric": "probe", "value": 1,
              "detail": {"platform": "cpu"}}, "ok")
    cpu_line = ({"metric": "sft_tokens_per_sec_per_chip", "value": 2.0},
                "ok")
    calls, got = _run_parent(monkeypatch, capsys, [
        ("probe", probe), ("cpu", cpu_line)])
    assert calls == ["probe", "cpu"]
    assert got["value"] == 2.0


def test_probe_line_with_timeout_still_skips_ladder(monkeypatch, capsys):
    """A probe child that printed its line but then wedged (timeout
    during teardown) demonstrated a wedge-class tunnel: the gate must
    look at status, not just at having parsed a line."""
    probe = ({"metric": "probe", "value": 1,
              "detail": {"platform": "tpu", "device_kind": "v5e"}},
             "timeout")
    cpu_line = ({"metric": "sft_tokens_per_sec_per_chip", "value": 4.0},
                "ok")
    calls, got = _run_parent(monkeypatch, capsys, [
        ("probe", probe), ("cpu", cpu_line)])
    assert calls == ["probe", "cpu"]
    assert got["value"] == 4.0


def test_probe_healthy_opens_ladder(monkeypatch, capsys):
    probe = ({"metric": "probe", "value": 1,
              "detail": {"platform": "tpu", "device_kind": "v5e"}}, "ok")
    accel = ({"metric": "sft_tokens_per_sec_per_chip", "value": 31000.0,
              "vs_baseline": 1.05}, "ok")
    calls, got = _run_parent(monkeypatch, capsys, [
        ("probe", probe), ("accel", accel)])
    assert calls == ["probe", "accel"]
    assert got["vs_baseline"] == 1.05


def test_probe_healthy_accel_oom_descends_then_cpu(monkeypatch, capsys):
    """OOM-class failures descend the micro ladder; exhausting it falls
    back to CPU."""
    probe = ({"metric": "probe", "value": 1,
              "detail": {"platform": "tpu", "device_kind": "v5e"}}, "ok")
    cpu_line = ({"metric": "sft_tokens_per_sec_per_chip", "value": 3.0},
                "ok")
    calls, got = _run_parent(monkeypatch, capsys, [
        ("probe", probe),
        ("accel", (None, "failed")), ("accel", (None, "failed")),
        ("accel", (None, "failed")), ("cpu", cpu_line)])
    assert calls == ["probe", "accel", "accel", "accel", "cpu"]
    assert got["value"] == 3.0


def test_sweep_variants_bind_to_run_variant():
    """Every variant BASELINE.md points at as a reproduction command must
    bind cleanly to run_variant's signature (a typo'd kwarg would only
    surface on the TPU, mid-measurement)."""
    import importlib.util
    import inspect
    import os

    tools_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    cited_by_tool = {
        # the exact reproduction commands BASELINE.md cites must resolve
        "sweep_bench.py": ("kv4_micro8_packed", "kv4_seq32k_micro1",
                           "kv4_micro8_b256", "hd128_kv4_micro8_bf16m"),
        # the r3 decode comparison point
        "sweep_decode.py": ("b8_bf16",),
    }
    for fname, cited in cited_by_tool.items():
        path = os.path.join(tools_dir, fname)
        spec = importlib.util.spec_from_file_location(fname[:-3], path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sig = inspect.signature(mod.run_variant)
        assert mod.VARIANTS, f"{fname} has no variants"
        for name, kw in mod.VARIANTS.items():
            sig.bind(name, **kw)  # raises TypeError on a bad kwarg
        for c in cited:
            assert c in mod.VARIANTS, f"BASELINE.md cites {fname}:{c}"


def test_sweep_decode_run_variant_smoke():
    """tools/sweep_decode.py run_variant end to end at toy scale on CPU:
    the artifact row must carry the metric fields BASELINE.md quotes,
    with finite positive values and a prefill-subtracted ms/token."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    import sweep_decode

    row = sweep_decode.run_variant(
        "smoke", batch=2, prompt=8, new=4, hidden=32, inter=64,
        layers=2, heads=2, kv_heads=1)
    # host-timer noise can push the prefill-SUBTRACTED fields near zero
    # on a contended CPU; the unsubtracted ones must be strictly positive
    for key in ("ms_per_token_incl_prefill", "roofline_ms"):
        assert row[key] > 0, (key, row)
    import math
    for key in ("ms_per_token", "decode_tok_s_chip", "x_roofline"):
        assert math.isfinite(row[key]), (key, row)
    assert row["params_m"] >= 0
    assert row["variant"] == "smoke"


def test_sweep_decode_int8_variant_smoke():
    """The int8-weights + int8-KV variant path (quantize_weights + the
    kernel gates) survives the same toy-scale drive."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    import sweep_decode

    row = sweep_decode.run_variant(
        "smoke8", batch=2, prompt=8, new=4, hidden=32, inter=64,
        layers=2, heads=2, kv_heads=1, kv_dtype="int8", weights="int8")
    assert row["ms_per_token"] > 0
    assert row["kv"] == "int8" and row["weights"] == "int8"


def test_sweep_decode_selfspec_variant_smoke():
    """Self-speculative variant: int8 tree drafts for its own target;
    must deliver tokens with a sane acceptance rate at toy scale."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    import sweep_decode

    row = sweep_decode.run_variant(
        "smoke_spec", batch=2, prompt=8, new=6, hidden=32, inter=64,
        layers=2, heads=2, kv_heads=1, speculative="selfint8", gamma=3)
    assert row["emitted"] > 0
    assert 0.0 <= row["accept_rate"] <= 1.0
    assert row["spec"] == "selfint8"
    assert row["verify_rounds"] >= 1
    import math
    assert math.isfinite(row["ms_per_token"])  # prefill-subtracted
