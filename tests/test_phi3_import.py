"""Phi-3 family: llama block semantics with FUSED qkv_proj /
gate_up_proj storage (split at import), sliding window with no
use_sliding_window knob. Logits parity with transformers'
Phi3ForCausalLM on a tiny random model saved to disk (zero egress)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_phi3_dir(tmp_path_factory):
    from transformers import Phi3Config, Phi3ForCausalLM
    cfg = Phi3Config(
        vocab_size=160, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5,
        rope_theta=10000.0, pad_token_id=0, tie_word_embeddings=False,
        sliding_window=8, attn_implementation="eager")
    torch.manual_seed(0)
    model = Phi3ForCausalLM(cfg).eval()
    d = tmp_path_factory.mktemp("hf_phi3")
    model.save_pretrained(str(d), safe_serialization=True)
    return d, model


def _load(d):
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    cfg = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none")
    return cfg, import_hf_weights(d, cfg)


def test_phi3_config_mapping(tiny_phi3_dir):
    d, _ = tiny_phi3_dir
    cfg, params = _load(d)
    assert cfg.arch == "llama"      # llama block, fused storage only
    assert not cfg.attention_bias
    # phi3 has no use_sliding_window knob: a set window applies
    assert cfg.sliding_window == 8
    # fused projections were split into the standard tree
    for k in ("wq", "wk", "wv", "w_gate", "w_up"):
        assert k in params["layers"], k
    assert params["layers"]["wq"].shape == (2, 32, 4 * 8)
    assert params["layers"]["wk"].shape == (2, 32, 2 * 8)


def test_phi3_import_matches_hf_logits(tiny_phi3_dir):
    d, hf_model = tiny_phi3_dir
    import jax.numpy as jnp
    from dla_tpu.models.transformer import Transformer

    cfg, params = _load(d)
    model = Transformer(cfg)
    rs = np.random.RandomState(0)
    # 12 tokens > window 8 so the sliding mask actually bites
    ids = rs.randint(1, 160, (2, 12))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_phi3_decode_matches_forward(tiny_phi3_dir):
    d, _ = tiny_phi3_dir
    import jax.numpy as jnp
    from dla_tpu.models.transformer import Transformer

    cfg, params = _load(d)
    model = Transformer(cfg)
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(1, 160, (1, 6)), jnp.int32)
    mask = jnp.ones((1, 6), jnp.int32)
    logits, cache = model.start_decode(params, ids, mask, 4)
    got = []
    for _ in range(4):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        got.append(int(tok[0]))
        logits, cache = model.decode_step(params, cache, tok)

    seq = list(np.asarray(ids[0]))
    want = []
    for _ in range(4):
        full = model.apply(params, jnp.asarray([seq], jnp.int32))
        nxt = int(np.argmax(np.asarray(full[0, -1])))
        want.append(nxt)
        seq.append(nxt)
    assert got == want


def test_phi3_longrope_matches_hf():
    """LongRoPE (phi-3 128k): per-dim factor lists, short below the
    original context and long beyond (a traced select matching HF's
    dynamic frequency update), cos/sin scaled by the derived attention
    factor. Unit parity vs ROPE_INIT_FUNCTIONS['longrope'] on both
    branches, then end-to-end logits parity on a tiny longrope phi-3."""
    import jax.numpy as jnp
    from transformers import Phi3Config, Phi3ForCausalLM
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    from dla_tpu.models.hf_import import _validated_rope_scaling
    from dla_tpu.ops.rotary import _longrope_inv_freq

    hd, theta, orig, ext = 16, 10000.0, 32, 4
    rng = np.random.RandomState(0)
    short = (1.0 + rng.rand(hd // 2) * 0.2).round(4).tolist()
    long = (2.0 + rng.rand(hd // 2) * 3.0).round(4).tolist()
    hf_cfg = Phi3Config(
        vocab_size=160, hidden_size=hd * 4, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=orig * ext,
        original_max_position_embeddings=orig,
        rope_theta=theta, pad_token_id=0, tie_word_embeddings=False,
        rope_scaling={"type": "longrope", "short_factor": short,
                      "long_factor": long},
        attn_implementation="eager")

    scaling = _validated_rope_scaling(hf_cfg.to_dict())
    assert scaling["rope_type"] == "longrope"
    assert scaling["original_max_position_embeddings"] == orig
    assert scaling["factor"] == ext
    inv0 = 1.0 / (theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    for seq_len in (orig - 4, orig * 2):
        inv_hf, att_hf = ROPE_INIT_FUNCTIONS["longrope"](
            hf_cfg, device="cpu", seq_len=seq_len)
        positions = jnp.arange(seq_len)[None, :]
        inv_j, att_j = _longrope_inv_freq(inv0, scaling, positions)
        np.testing.assert_allclose(np.asarray(inv_j), inv_hf.numpy(),
                                   rtol=1e-6, err_msg=f"seq={seq_len}")
        assert abs(att_j - float(att_hf)) < 1e-9

    # end to end, on BOTH sides of the original context
    import tempfile

    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    from dla_tpu.models.transformer import Transformer

    torch.manual_seed(2)
    hf_model = Phi3ForCausalLM(hf_cfg).eval()
    with tempfile.TemporaryDirectory() as d:
        hf_model.save_pretrained(d, safe_serialization=True)
        cfg = hf_config_to_model_config(
            read_hf_config(d), dtype="float32", param_dtype="float32",
            remat="none")
        params = import_hf_weights(d, cfg)
    model = Transformer(cfg)
    for t in (orig - 8, orig + 24):   # short branch, then long branch
        ids = np.random.RandomState(4).randint(0, 160, (2, t))
        ours = np.asarray(model.apply(params, jnp.asarray(ids, np.int32)))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(ids)).logits.numpy()
        np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=3e-4,
                                   err_msg=f"T={t}")
