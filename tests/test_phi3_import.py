"""Phi-3 family: llama block semantics with FUSED qkv_proj /
gate_up_proj storage (split at import), sliding window with no
use_sliding_window knob. Logits parity with transformers'
Phi3ForCausalLM on a tiny random model saved to disk (zero egress)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_phi3_dir(tmp_path_factory):
    from transformers import Phi3Config, Phi3ForCausalLM
    cfg = Phi3Config(
        vocab_size=160, hidden_size=32, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5,
        rope_theta=10000.0, pad_token_id=0, tie_word_embeddings=False,
        sliding_window=8, attn_implementation="eager")
    torch.manual_seed(0)
    model = Phi3ForCausalLM(cfg).eval()
    d = tmp_path_factory.mktemp("hf_phi3")
    model.save_pretrained(str(d), safe_serialization=True)
    return d, model


def _load(d):
    from dla_tpu.models.hf_import import (
        hf_config_to_model_config,
        import_hf_weights,
        read_hf_config,
    )
    cfg = hf_config_to_model_config(
        read_hf_config(d), dtype="float32", param_dtype="float32",
        remat="none")
    return cfg, import_hf_weights(d, cfg)


def test_phi3_config_mapping(tiny_phi3_dir):
    d, _ = tiny_phi3_dir
    cfg, params = _load(d)
    assert cfg.arch == "llama"      # llama block, fused storage only
    assert not cfg.attention_bias
    # phi3 has no use_sliding_window knob: a set window applies
    assert cfg.sliding_window == 8
    # fused projections were split into the standard tree
    for k in ("wq", "wk", "wv", "w_gate", "w_up"):
        assert k in params["layers"], k
    assert params["layers"]["wq"].shape == (2, 32, 4 * 8)
    assert params["layers"]["wk"].shape == (2, 32, 2 * 8)


def test_phi3_import_matches_hf_logits(tiny_phi3_dir):
    d, hf_model = tiny_phi3_dir
    import jax.numpy as jnp
    from dla_tpu.models.transformer import Transformer

    cfg, params = _load(d)
    model = Transformer(cfg)
    rs = np.random.RandomState(0)
    # 12 tokens > window 8 so the sliding mask actually bites
    ids = rs.randint(1, 160, (2, 12))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)


def test_phi3_decode_matches_forward(tiny_phi3_dir):
    d, _ = tiny_phi3_dir
    import jax.numpy as jnp
    from dla_tpu.models.transformer import Transformer

    cfg, params = _load(d)
    model = Transformer(cfg)
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(1, 160, (1, 6)), jnp.int32)
    mask = jnp.ones((1, 6), jnp.int32)
    logits, cache = model.start_decode(params, ids, mask, 4)
    got = []
    for _ in range(4):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        got.append(int(tok[0]))
        logits, cache = model.decode_step(params, cache, tok)

    seq = list(np.asarray(ids[0]))
    want = []
    for _ in range(4):
        full = model.apply(params, jnp.asarray([seq], jnp.int32))
        nxt = int(np.argmax(np.asarray(full[0, -1])))
        want.append(nxt)
        seq.append(nxt)
    assert got == want
