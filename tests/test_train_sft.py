"""End-to-end SFT smoke tests on the 8-device CPU mesh: loss falls, packing
works, checkpoints are written, resume continues, and the sharded step
matches a single-axis run (SURVEY.md sec 4 items 3-4)."""
import json

import numpy as np
import pytest
import yaml

from dla_tpu.data.jsonl import read_jsonl, write_jsonl


def _write_sft_config(tmp_path, n_records=64, **overrides):
    data_path = tmp_path / "sft_train.jsonl"
    rng = np.random.default_rng(0)
    recs = []
    for i in range(n_records):
        a, b = int(rng.integers(0, 50)), int(rng.integers(0, 50))
        recs.append({"prompt": f"add {a} {b}", "response": str(a + b)})
    write_jsonl(data_path, recs)
    cfg = {
        "experiment_name": "sft_smoke",
        "seed": 0,
        "model": {"model_name_or_path": "tiny", "max_seq_length": 32,
                  "tokenizer": "byte"},
        "data": {"source": "local", "train_path": str(data_path)},
        "optimization": {
            "total_batch_size": 16, "micro_batch_size": 2,
            "learning_rate": 1e-3, "warmup_steps": 2,
            "max_train_steps": 20, "lr_scheduler": "cosine",
            "max_grad_norm": 1.0,
        },
        "logging": {
            "output_dir": str(tmp_path / "ckpt"),
            "log_dir": str(tmp_path / "logs"),
            "log_every_steps": 2, "eval_every_steps": 0,
            "save_every_steps": 6,
        },
        "hardware": {
            "gradient_accumulation_steps": 2,
            "mesh": {"data": 2, "fsdp": 2, "model": 2, "sequence": 1},
        },
    }
    for dotted, v in overrides.items():
        node = cfg
        keys = dotted.split(".")
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = v
    cfg_path = tmp_path / "sft.yaml"
    cfg_path.write_text(yaml.safe_dump(cfg))
    return cfg_path, cfg


def _losses(log_dir):
    path = log_dir / "metrics.jsonl"
    out = []
    with open(path) as fh:
        for line in fh:
            rec = json.loads(line)
            if "train/loss_instant" in rec:
                out.append((rec["step"], rec["train/loss_instant"]))
    return out


def test_sft_end_to_end_loss_falls(tmp_path):
    from dla_tpu.training.train_sft import main
    cfg_path, cfg = _write_sft_config(tmp_path)
    main(["--config", str(cfg_path)])
    losses = _losses(tmp_path / "logs")
    assert losses, "no metrics logged"
    first, last = losses[0][1], losses[-1][1]
    assert last < first * 0.9, f"loss did not fall: {first} -> {last}"
    # checkpoints: periodic + final, with latest pointer
    ckpt = tmp_path / "ckpt"
    assert (ckpt / "latest").is_file()
    assert (ckpt / "final").is_dir()
    # metrics include the north-star throughput metric
    with open(tmp_path / "logs" / "metrics.jsonl") as fh:
        rec = json.loads(fh.readlines()[-1])
    assert "tokens_per_sec_per_chip" in rec


def test_sft_bf16_grad_accum(tmp_path):
    """optimization.grad_accum_dtype: bfloat16 (the 70B HBM lever —
    halves the whole-tree accumulation transient): training still
    converges, and a bogus dtype is refused at trainer construction."""
    from dla_tpu.training.train_sft import main
    cfg_path, cfg = _write_sft_config(
        tmp_path, **{"optimization.grad_accum_dtype": "bfloat16"})
    main(["--config", str(cfg_path)])
    losses = _losses(tmp_path / "logs")
    assert losses and losses[-1][1] < losses[0][1] * 0.95

    import pytest

    from dla_tpu.training.trainer import Trainer
    with pytest.raises(ValueError, match="grad_accum_dtype"):
        Trainer(config={**cfg, "optimization": {
                    **cfg["optimization"], "grad_accum_dtype": "float16"}},
                mesh=None, loss_fn=None, params=None, param_specs=None)


def test_sft_resume_continues(tmp_path):
    from dla_tpu.training.train_sft import main
    cfg_path, cfg = _write_sft_config(tmp_path)
    main(["--config", str(cfg_path)])
    # bump max steps and resume from final state
    cfg["optimization"]["max_train_steps"] = 24
    cfg_path.write_text(yaml.safe_dump(cfg))
    main(["--config", str(cfg_path), "--resume"])
    losses = _losses(tmp_path / "logs")
    steps = [s for s, _ in losses]
    assert max(steps) == 24
    # resume must not restart from 0: step 2 logged exactly once
    assert steps.count(2) == 1


def test_sft_with_packing(tmp_path):
    from dla_tpu.training.train_sft import main
    cfg_path, cfg = _write_sft_config(
        tmp_path, **{"data.packing": True,
                     "optimization.max_train_steps": 4,
                     "optimization.total_batch_size": 8,
                     "optimization.micro_batch_size": 1,
                     "hardware.gradient_accumulation_steps": 2})
    main(["--config", str(cfg_path)])
    losses = _losses(tmp_path / "logs")
    assert losses and np.isfinite(losses[-1][1])


def test_sft_overlay_and_override(tmp_path):
    """Ablation overlays merge (reference merged them by hand) and dotted
    --set overrides apply."""
    from dla_tpu.training.config import load_config
    cfg_path, _ = _write_sft_config(tmp_path)
    overlay = tmp_path / "low_lr.yaml"
    overlay.write_text(yaml.safe_dump(
        {"optimization": {"learning_rate": 5e-6}}))
    cfg = load_config(cfg_path, overlays=[str(overlay)],
                      overrides=["optimization.max_grad_norm=0.5"], quiet=True)
    assert cfg["optimization"]["learning_rate"] == 5e-6
    assert cfg["optimization"]["max_grad_norm"] == 0.5
    assert cfg["optimization"]["total_batch_size"] == 16  # untouched
