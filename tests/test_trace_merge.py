"""Distributed-tracing tests (telemetry/trace_context.py +
tools/trace_merge.py): traceparent round-trips, the zero-work-when-
disabled pin extended to the span spool, torn-spool tolerance, clock
alignment edge cases (known skew recovered from beat pairs, single-beat
one-way peers, wall-anchor fallback, beats beating contradictory wall
clocks, causal clamping), the SamplerFleet chaos timeline (reassignment
is a CHILD of the dispatch it replaced), and the cross-process
acceptance: two subprocess gateway fleets behind a FederatedRouter with
a mid-stream migration merge into ONE valid Chrome trace whose span
trees cross process boundaries with correct parent links."""
import json
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from dla_tpu.telemetry.trace import Tracer, get_tracer, install_tracer
from dla_tpu.telemetry.trace_context import (
    TRACEPARENT_HEADER,
    SpanSpool,
    TraceContext,
    open_spool,
    read_spool,
    spool_paths,
)
from tools.trace_merge import (
    MergeError,
    align,
    load_dir,
    merge_dir,
    self_check,
    span_trees,
    validate,
)

REPO_ROOT = Path(__file__).resolve().parent.parent

T1 = "0af7651916cd43dd8448eb211c80319c"          # fixture-style ids
S1, S2, S3 = "b7ad6b7169203331", "00f067aa0ba902a1", "53ce929d0e0e4736"


# ---------------------------------------------------------------------------
# TraceContext
# ---------------------------------------------------------------------------

def test_traceparent_mint_child_header_roundtrip():
    root = TraceContext.mint()
    assert len(root.trace_id) == 32 and len(root.span_id) == 16
    child = root.child()
    assert child.trace_id == root.trace_id
    assert child.span_id != root.span_id
    back = TraceContext.from_header(root.to_header())
    assert back == root
    assert root.to_header().startswith("00-")
    # tags carry (trace, span) and the parent link when known
    tags = child.tags(root)
    assert tags == {"trace": root.trace_id, "span": child.span_id,
                    "parent": root.span_id}
    assert "parent" not in root.tags()
    # dict round-trip (the MigrationTicket / TrajectoryGroup carrier)
    assert TraceContext.from_dict(root.to_dict()) == root


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-short-b7ad6b7169203331-01",
    f"00-{T1}-tooshort-01", f"00-{T1}-{S1}", f"00-{'z' * 32}-{S1}-01",
])
def test_traceparent_malformed_header_is_untraced_not_error(bad):
    assert TraceContext.from_header(bad) is None
    assert TraceContext.from_dict({"trace_id": 7}) is None


# ---------------------------------------------------------------------------
# the zero-work pin extends to the spool
# ---------------------------------------------------------------------------

class _RaisingSpool(SpanSpool):
    """Every record write raises — a disabled tracer must never get
    here (trace.py's zero-producer-work contract, spool edition)."""

    def __init__(self):
        super().__init__("/nonexistent/never-opened.jsonl", "raising")
        self.anchored = 0

    def anchor(self, t0):         # attach-time anchor is allowed
        self.anchored += 1

    def write(self, rec):
        raise AssertionError("disabled tracer reached the spool")


def test_disabled_tracer_never_reaches_spool():
    tr = Tracer(enabled=False)
    tr.attach_spool(_RaisingSpool())
    t = tr.now()
    with tr.span("s", "cat"):
        pass
    tr.complete("c", t, tr.now(), cat="cat", args={"x": 1})
    tr.instant("i")
    tr.async_begin("cat", "a", 1)
    tr.async_end("cat", "a", 1)
    assert tr.emitted == 0 and tr.spooled == 0 and tr.spool_errors == 0
    # flipping enabled on proves the spool WOULD have been reached
    tr.enabled = True
    with pytest.raises(AssertionError):
        tr.complete("c", t, tr.now())


def test_spool_write_failures_counted_never_raised(tmp_path):
    sp = open_spool(str(tmp_path), "proc/with:odd chars")
    assert "spans_" in sp.path.name and "/" not in sp.path.name
    sp.write({"k": "span", "bad": float("nan")})    # not strict JSON
    assert sp.errors == 1 and sp.written == 0
    sp.event({"name": "ok", "ph": "X", "ts": 0.0, "dur": 1.0})
    assert sp.written == 1
    sp.close()
    assert spool_paths(str(tmp_path)) == [sp.path]


# ---------------------------------------------------------------------------
# synthetic spools: alignment edge cases
# ---------------------------------------------------------------------------

def _ev(name, ts_us, trace=None, span=None, parent=None, dur=50.0):
    ev = {"name": name, "ph": "X", "ts": float(ts_us),
          "dur": float(dur), "tid": 0}
    if trace is not None:
        args = {"trace": trace, "span": span}
        if parent is not None:
            args["parent"] = parent
        ev["args"] = args
    return ev


def _write_spool(dirpath, proc, pid, mono, wall, events,
                 beats_sent=(), beats_seen=(), torn=False):
    """Hand-author one spool file. ``mono``/``wall`` anchor the process
    clocks with perf == t0 == 0, so an event's monotonic time is simply
    ``mono + ts/1e6``."""
    lines = [json.dumps({"k": "clock", "proc": proc, "pid": pid,
                         "perf": 0.0, "mono": mono, "wall": wall,
                         "t0": 0.0})]
    for ev in events:
        lines.append(json.dumps({"k": "span", "proc": proc, "ev": ev}))
    for peer, seq, m in beats_sent:
        lines.append(json.dumps({"k": "beat_sent", "proc": proc,
                                 "peer": peer, "seq": seq, "mono": m}))
    for peer, seq, m in beats_seen:
        lines.append(json.dumps({"k": "beat_seen", "proc": proc,
                                 "peer": peer, "seq": seq, "mono": m}))
    text = "\n".join(lines) + "\n"
    if torn:
        text += '{"k": "span", "proc": "' + proc + '", "ev": {"na'
    path = Path(dirpath) / f"spans_{proc}_{pid}.jsonl"
    path.write_text(text)
    return path


def test_known_skew_recovered_from_paired_beats(tmp_path):
    """Two procs, true monotonic offset 4900 s, bidirectional beats with
    asymmetric lags (20 ms / 10 ms): the paired (NTP-midpoint) estimate
    must land within the lag bound, and the contradictory wall clocks
    (which agree exactly — implying offset ~0) must NOT win."""
    # A is the busier proc -> reference
    _write_spool(
        tmp_path, "A", 1, mono=100.0, wall=1000.0,
        events=[_ev("root", 0.0, T1, S1),
                _ev("left", 10.0, T1, S3, parent=S1),
                _ev("pad", 20.0)],
        beats_sent=[("A", 1, 100.0), ("A", 2, 100.2)],
        beats_seen=[("B", 1, 100.51)])
    _write_spool(
        tmp_path, "B", 2, mono=5000.0, wall=1000.0,
        events=[_ev("remote", 30.0, T1, S2, parent=S1)],
        beats_sent=[("B", 1, 5000.5)],
        beats_seen=[("A", 1, 5000.02), ("A", 2, 5000.21)])
    procs = load_dir(str(tmp_path))["procs"]
    off = align(procs)
    assert off["A"]["method"] == "reference"
    assert off["B"]["method"] == "paired"
    # true offset is -4900 (B's monotonic reads 4900 ahead of A's);
    # estimate must sit inside the [10 ms, 20 ms] lag bracket
    assert abs(off["B"]["offset"] + 4900.0) < 0.02
    doc = merge_dir(str(tmp_path))
    assert validate(doc) == []
    trees = span_trees(doc)
    assert len(trees[T1]["procs"]) == 2         # one tree, two pids
    assert trees[T1]["unresolved"] == []


def test_single_beat_peer_aligns_one_way(tmp_path):
    _write_spool(tmp_path, "A", 1, mono=0.0, wall=500.0,
                 events=[_ev("a", 0.0, T1, S1), _ev("pad", 5.0)],
                 beats_sent=[("A", 7, 1.0)])
    _write_spool(tmp_path, "B", 2, mono=300.0, wall=999.0,
                 events=[_ev("b", 0.0, T1, S2, parent=S1)],
                 beats_seen=[("A", 7, 301.015)])
    off = align(load_dir(str(tmp_path))["procs"])
    assert off["B"]["method"] == "one_way"
    # the single one-sided bound IS the estimate: -300.015
    assert abs(off["B"]["offset"] + 300.015) < 1e-9
    assert validate(merge_dir(str(tmp_path))) == []


def test_beatless_peer_falls_back_to_wall_anchor(tmp_path):
    _write_spool(tmp_path, "A", 1, mono=100.0, wall=1000.0,
                 events=[_ev("a", 0.0, T1, S1), _ev("pad", 5.0)])
    _write_spool(tmp_path, "B", 2, mono=5000.0, wall=1000.5,
                 events=[_ev("b", 0.0, T1, S2, parent=S1)])
    off = align(load_dir(str(tmp_path))["procs"])
    assert off["B"]["method"] == "wall"
    # wall anchors say B's event happened 0.5 s after A's
    assert abs(off["B"]["offset"] + 4899.5) < 1e-6
    doc = merge_dir(str(tmp_path))
    assert doc["otherData"]["procs"]["B"]["method"] == "wall"
    assert validate(doc) == []


def test_torn_trailing_record_skipped_not_crashed(tmp_path):
    p = _write_spool(tmp_path, "A", 1, mono=0.0, wall=0.0,
                     events=[_ev("a", 0.0, T1, S1)], torn=True)
    recs, skipped = read_spool(str(p))
    assert skipped == 1 and len(recs) == 2      # clock + span survive
    doc = merge_dir(str(tmp_path))
    assert doc["otherData"]["skipped_lines"] == 1
    assert validate(doc) == []


def test_causal_clamp_child_never_starts_before_parent(tmp_path):
    """A one-way peer's residual lag can place a child hop BEFORE its
    parent; the merger must clamp it (monotone parent links) and emit
    cross-process flow arrows for the stitched link."""
    _write_spool(tmp_path, "A", 1, mono=0.0, wall=0.0,
                 events=[_ev("parent", 1000.0, T1, S1), _ev("pad", 5.0)],
                 beats_sent=[("A", 1, 0.0)])
    # aligned naively, the child lands at ts 0 — 1 ms before its parent
    _write_spool(tmp_path, "B", 2, mono=50.0, wall=0.0,
                 events=[_ev("child", 0.0, T1, S2, parent=S1)],
                 beats_seen=[("A", 1, 50.0)])
    doc = merge_dir(str(tmp_path))
    assert validate(doc) == []
    assert doc["otherData"]["clamped"] >= 1
    flows = [e for e in doc["traceEvents"]
             if e.get("cat") == "traceflow"]
    assert {e["ph"] for e in flows} == {"s", "f"}
    tree = span_trees(doc)[T1]
    assert tree["spans"][S2]["ts"] >= tree["spans"][S1]["ts"]


def test_empty_dir_raises_merge_error(tmp_path):
    with pytest.raises(MergeError):
        merge_dir(str(tmp_path))


def test_self_check_fixture_green():
    assert self_check() == 0


# ---------------------------------------------------------------------------
# SamplerFleet chaos: reassignment is a child of the original dispatch
# ---------------------------------------------------------------------------

def test_fleet_reassign_span_children_of_original_dispatch():
    import jax
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.sampling import derive_rollout_seeds
    from dla_tpu.rollout import SamplerFleet, SamplerFleetConfig
    from dla_tpu.serving.server import ServingConfig

    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    rs = np.random.RandomState(3)
    prompts = [list(rs.randint(3, 500, (n,))) for n in (6, 4, 9, 5)]
    width = max(len(p) for p in prompts)
    ids = np.zeros((len(prompts), width), np.int32)
    mask = np.zeros_like(ids)
    for i, p in enumerate(prompts):
        ids[i, :len(p)] = p
        mask[i, :len(p)] = 1
    gen = GenerationConfig(max_new_tokens=5, do_sample=True,
                           temperature=0.9, top_p=0.9, top_k=8,
                           eos_token_id=2, pad_token_id=0)
    seeds = derive_rollout_seeds(123, len(ids))

    prev = get_tracer()
    tracer = Tracer(enabled=True, capacity=1 << 16)
    install_tracer(tracer)
    fleet = SamplerFleet(
        model, params, gen,
        ServingConfig(page_size=4, num_pages=64, num_slots=3,
                      max_model_len=32, max_prefill_batch=2,
                      fault_plan="sampler=1:rollout_step=0:lost"),
        SamplerFleetConfig(samplers=2, lease_ttl_s=0.3))
    try:
        fleet.generate(ids, mask, seeds)
        assert fleet.fleet_metrics.snapshot()[
            "rollout/fleet/reassigned_rollouts"] >= 1
    finally:
        fleet.close()
        install_tracer(prev)

    evs = [e for e in tracer.export()["traceEvents"]
           if e.get("ph") == "X"]
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e.get("args") or {})
    roots = by_name.get("fleet_rollout", [])
    dispatches = by_name.get("sampler_dispatch", [])
    reassigns = by_name.get("sampler_reassign_dispatch", [])
    drives = by_name.get("sampler_drive", [])
    assert roots and dispatches and reassigns and drives
    trace_id = roots[0]["trace"]
    # one shared trace id across every hop of the rollout
    assert all(a["trace"] == trace_id
               for a in dispatches + reassigns + drives)
    # initial dispatches parent under the rollout root...
    assert {a["parent"] for a in dispatches} == {roots[0]["span"]}
    # ...and EVERY reassignment parents under an ORIGINAL dispatch span
    # (the acceptance bar: the merged timeline shows reassignment as a
    # child of the dispatch it replaced, not a fresh root)
    dispatch_spans = {a["span"] for a in dispatches}
    for a in reassigns:
        assert a["parent"] in dispatch_spans
    # each drive parents under ITS dispatch (initial or reassign)
    all_dispatch_spans = dispatch_spans | {a["span"] for a in reassigns}
    for a in drives:
        assert a["parent"] in all_dispatch_spans


# ---------------------------------------------------------------------------
# cross-process acceptance: two fleets + router + mid-stream migration
# ---------------------------------------------------------------------------

def test_cross_process_merge_with_midstream_migration(tmp_path):
    """Two SUBPROCESS gateway-fronted fleets behind a FederatedRouter,
    every process spooling spans into one shared dir; a request is
    caught mid-stream on the slow peer and migrated. The merged doc
    must be ONE valid Chrome trace where every federated request's span
    tree crosses the router AND a worker process with resolved parent
    links, the migrated request's tree touches all three processes, and
    no process fell back to wall-clock alignment."""
    sys.path.insert(0, str(REPO_ROOT))
    from _cpuhost import scrubbed_cpu_env
    from dla_tpu.serving import FederatedRouter, FederationConfig

    gossip = tmp_path / "gossip"
    spool = tmp_path / "spool"
    gossip.mkdir()
    spool.mkdir()
    env = scrubbed_cpu_env(1, str(REPO_ROOT))
    rs = np.random.RandomState(11)
    prompts = [[int(t) for t in rs.randint(3, 500, (6,))]
               for _ in range(4)]

    prev = get_tracer()
    install_tracer(Tracer.from_config(
        {"enabled": True, "capacity": 1 << 17,
         "spool_dir": str(spool), "proc": "router"}))
    procs = {}
    fed = FederatedRouter(gossip, FederationConfig())
    try:
        for name, slow_ms in (("a", "25"), ("b", "0")):
            procs[name] = subprocess.Popen(
                [sys.executable,
                 str(REPO_ROOT / "tests" / "_gateway_worker.py"),
                 str(gossip), name, slow_ms, str(spool)],
                env=env, cwd=str(REPO_ROOT),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
        deadline = time.monotonic() + 600
        while len(fed.live_peers()) < 2:
            assert time.monotonic() < deadline, "peers never came up"
            time.sleep(0.05)

        fids = [fed.submit(p, 6) for p in prompts]
        fed.results(timeout_s=600)

        # catch one request mid-stream on the slow peer, then move it
        moved = None
        for _ in range(6):
            f = fed.submit(prompts[0], 8)
            fr = fed._requests[f]
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if fr.peer == "a" and fr.remote_rid is not None \
                        and len(fr.tokens) >= 2 \
                        and fr.state == "pending":
                    moved = f
                    break
                if fr.state != "pending":
                    break
                time.sleep(0.01)
            if moved is not None:
                break
            fed.results(timeout_s=300)
        assert moved is not None, "never caught a mid-stream request"
        fed.migrate(moved, "b")
        out = fed.results(timeout_s=600)
        assert out[moved].state == "finished"
        migrated_trace = fed._requests[moved].trace.trace_id
        traces = {f: fed._requests[f].trace.trace_id
                  for f in fids + [moved]}
    finally:
        for p in procs.values():
            p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
        tr = get_tracer()
        assert tr.dropped == 0 and tr.spool_errors == 0
        tr.detach_spool()
        install_tracer(prev)

    assert len(spool_paths(str(spool))) == 3    # router + two workers
    doc = merge_dir(str(spool))
    assert validate(doc) == []
    other = doc["otherData"]
    assert set(other["procs"]) == {"router", "a", "b"}
    # beats flow worker->router; nobody may need wall clocks
    assert all(p["method"] in ("reference", "paired", "one_way")
               for p in other["procs"].values())
    trees = span_trees(doc)
    for f, tid in traces.items():
        tree = trees.get(tid)
        assert tree is not None, f"request {f}: no spans merged"
        assert tree["unresolved"] == []
        assert len(tree["procs"]) >= 2, \
            f"request {f}'s span tree never crossed a process boundary"
    # the migrated request's tree touches router + source + target
    assert len(trees[migrated_trace]["procs"]) == 3
