"""dla-lint framework tests (docs/ANALYSIS.md).

THE pins: (a) every rule fires on its bad fixture and stays silent on
the good twin — the firing fixtures double as executable documentation
of what each rule means; (b) the repo itself lints clean: zero
unsuppressed findings over dla_tpu/ + tools/ + bench.py + config/, in
under the 10 s acceptance bound, and every suppression carries a human
reason; (c) the JSON report is the shared strict ``dla-report/1``
schema — the same validator accepts dla-lint and metrics_diff output;
(d) baselines match by (rule, path, source-line) fingerprint and so
survive pure line-number drift; (e) CLI exit codes follow the 0/1/2
convention.
"""
import json
import os
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from dla_tpu.analysis import all_rules, run_lint  # noqa: E402
from dla_tpu.analysis.cli import main as lint_main  # noqa: E402
from dla_tpu.analysis.report import (  # noqa: E402
    SCHEMA_ID,
    apply_baseline,
    dump_baseline,
    dump_report,
    lint_json_report,
    load_baseline,
    validate_report,
)

ALL_RULE_NAMES = {
    "retrace-hazard", "trace-side-effect", "host-sync-in-hot-loop",
    "donation-misuse", "pallas-tiling", "config-schema-drift",
    "metric-name-drift", "unsynchronized-shared-state",
    "lock-order-inversion", "blocking-under-lock",
    "conditional-collective",
}


def lint_src(tmp_path, src, rules=None, name="mod.py"):
    """Write one fixture file and return the active rule names hit."""
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    result = run_lint([p], rules=rules, root=tmp_path)
    return result


def fired(result):
    return {f.rule for f in result.active}


# --------------------------------------------------------------- registry

def test_rule_catalog_is_complete():
    rules = all_rules()
    assert set(rules) == ALL_RULE_NAMES
    for name, rule in rules.items():
        assert rule.name == name and rule.summary


# ---------------------------------------------------------- retrace-hazard

def test_retrace_hazard_fires_on_python_branch_on_traced_arg(tmp_path):
    r = lint_src(tmp_path, """
        import jax

        @jax.jit
        def f(x, n):
            if n > 0:
                return x + n
            return x
        """)
    assert "retrace-hazard" in fired(r)


def test_retrace_hazard_silent_with_static_argnums(tmp_path):
    r = lint_src(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(1,))
        def f(x, n):
            if n > 0:
                return x + n
            return x
        """)
    assert "retrace-hazard" not in fired(r)


def test_retrace_hazard_fires_on_traced_shape(tmp_path):
    r = lint_src(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(n):
            return jnp.zeros(n)
        """)
    assert "retrace-hazard" in fired(r)


def test_retrace_hazard_split_key_is_not_a_shape(tmp_path):
    # jax.random.split's first arg is the (traced) key — only its `num`
    # argument is shape-like. Regression test for the self-apply pass.
    ok = lint_src(tmp_path, """
        import jax

        @jax.jit
        def g(key):
            return jax.random.split(key, 4)
        """)
    assert "retrace-hazard" not in fired(ok)
    bad = lint_src(tmp_path, """
        import jax

        @jax.jit
        def g(key, n):
            return jax.random.split(key, n)
        """, name="bad_split.py")
    assert "retrace-hazard" in fired(bad)


# ------------------------------------------------------- trace-side-effect

def test_trace_side_effect_fires_inside_jit(tmp_path):
    r = lint_src(tmp_path, """
        import jax
        import time

        @jax.jit
        def f(x):
            t = time.time()
            print(x)
            return x
        """)
    assert "trace-side-effect" in fired(r)
    assert len([f for f in r.active if f.rule == "trace-side-effect"]) == 2


def test_trace_side_effect_silent_outside_jit(tmp_path):
    r = lint_src(tmp_path, """
        import time

        def f(x):
            t = time.time()
            print(x)
            return x
        """)
    assert "trace-side-effect" not in fired(r)


# --------------------------------------------------- host-sync-in-hot-loop

def test_host_sync_fires_via_pragma_root_and_call_chain(tmp_path):
    r = lint_src(tmp_path, """
        def hot(xs):  # dla: hot-loop-root
            for x in xs:
                helper(x)

        def helper(x):
            return x.item()
        """)
    hits = [f for f in r.active if f.rule == "host-sync-in-hot-loop"]
    assert hits and "hot -> helper" in hits[0].message


def test_host_sync_fires_from_trainer_fit_root(tmp_path):
    r = lint_src(tmp_path, """
        class Trainer:
            def fit(self, xs):
                for x in xs:
                    v = float(x)
        """)
    assert "host-sync-in-hot-loop" in fired(r)


def test_host_sync_silent_without_a_root(tmp_path):
    r = lint_src(tmp_path, """
        def cold(xs):
            return [x.item() for x in xs]
        """)
    assert "host-sync-in-hot-loop" not in fired(r)


# --------------------------------------------------------- donation-misuse

def test_donation_misuse_fires_on_use_after_donate(tmp_path):
    r = lint_src(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def train_step(state, batch):
            return state

        def loop(state, batches):
            for b in batches:
                new_state = train_step(state, b)
                log(state)
                state = new_state
            return state
        """)
    assert "donation-misuse" in fired(r)


def test_donation_misuse_silent_on_same_statement_rebind(tmp_path):
    r = lint_src(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def train_step(state, batch):
            return state

        def loop(state, batches):
            for b in batches:
                state = train_step(state, b)
            return state
        """)
    assert "donation-misuse" not in fired(r)


# ----------------------------------------------------------- pallas-tiling

def test_pallas_tiling_fires_off_tile_and_missing_interpret(tmp_path):
    r = lint_src(tmp_path, """
        from jax.experimental import pallas as pl

        def launch(x, kernel):
            spec = pl.BlockSpec((8, 100), lambda i: (i, 0))
            return pl.pallas_call(kernel)(x)
        """)
    msgs = [f.message for f in r.active if f.rule == "pallas-tiling"]
    assert any("multiple of 128" in m for m in msgs)
    assert any("interpret" in m for m in msgs)


def test_pallas_tiling_silent_on_tile_aligned_with_fallback(tmp_path):
    r = lint_src(tmp_path, """
        from jax.experimental import pallas as pl

        def launch(x, kernel, interpret=False):
            spec = pl.BlockSpec((8, 128), lambda i: (i, 0))
            return pl.pallas_call(kernel, interpret=interpret)(x)
        """)
    assert "pallas-tiling" not in fired(r)


# ----------------------------------------------------- config-schema-drift

def test_config_schema_drift_fires_with_suggestion(tmp_path):
    p = tmp_path / "config" / "exp.yaml"
    p.parent.mkdir()
    p.write_text("experiment_name: t\nmodel:\n  max_seq_lenght: 128\n")
    r = run_lint([p], rules=["config-schema-drift"], root=tmp_path)
    hits = [f for f in r.active if f.rule == "config-schema-drift"]
    assert hits and "max_seq_length" in hits[0].message


def test_config_schema_drift_silent_on_declared_keys(tmp_path):
    p = tmp_path / "config" / "exp.yaml"
    p.parent.mkdir()
    p.write_text("experiment_name: t\nseed: 0\nmodel:\n"
                 "  max_seq_length: 128\n")
    r = run_lint([p], rules=["config-schema-drift"], root=tmp_path)
    assert "config-schema-drift" not in fired(r)


# ------------------------------------------------------- metric-name-drift

def test_metric_name_drift_fires_on_undeclared_name(tmp_path):
    r = lint_src(tmp_path,
                 'M = "train/not_a_real_metric_xyz"\n',
                 rules=["metric-name-drift"])
    hits = [f for f in r.active if f.rule == "metric-name-drift"]
    assert hits and hits[0].data["name"] == "train/not_a_real_metric_xyz"


def test_metric_name_drift_silent_on_catalog_name(tmp_path):
    r = lint_src(tmp_path, 'M = "train/loss"\n',
                 rules=["metric-name-drift"])
    assert "metric-name-drift" not in fired(r)


def test_check_metric_names_shim_delegates_to_rule(tmp_path, capsys):
    from tools.check_metric_names import run
    (tmp_path / "dla_tpu").mkdir()
    (tmp_path / "dla_tpu" / "x.py").write_text(
        'm = "train/ghost_metric"  '
        '# dla: disable=metric-name-drift -- fixture\n')
    (tmp_path / "bench.py").write_text("")
    # pragma honored through the shim: framework semantics for free
    assert run(tmp_path) == 0


# ------------------------------------------------------------ suppressions

def test_suppression_inline_and_reason_carried(tmp_path):
    r = lint_src(tmp_path, """
        import jax

        @jax.jit
        def f(x, n):
            if n > 0:  # dla: disable=retrace-hazard -- bounded by caller
                return x + n
            return x
        """)
    assert not r.active
    assert r.suppressed and r.suppressed[0].reason == "bounded by caller"


def test_suppression_standalone_comment_covers_next_line(tmp_path):
    r = lint_src(tmp_path, """
        import jax

        @jax.jit
        def f(x, n):
            # dla: disable=retrace-hazard -- fixture
            if n > 0:
                return x + n
            return x
        """)
    assert not r.active and r.suppressed


def test_suppression_file_level_and_all_wildcard(tmp_path):
    r = lint_src(tmp_path, """
        # dla: disable-file=all -- generated fixture
        import jax
        import time

        @jax.jit
        def f(x, n):
            t = time.time()
            if n > 0:
                return x + n
            return x
        """)
    assert not r.active and len(r.suppressed) >= 2


def test_wrong_rule_suppression_does_not_hide(tmp_path):
    r = lint_src(tmp_path, """
        import jax

        @jax.jit
        def f(x, n):
            if n > 0:  # dla: disable=pallas-tiling -- wrong rule
                return x + n
            return x
        """)
    assert "retrace-hazard" in fired(r)


# -------------------------------------------------------------- the report

def test_json_report_is_strict_and_round_trips(tmp_path):
    r = lint_src(tmp_path, """
        import jax

        @jax.jit
        def f(x, n):
            if n > 0:
                return x + n
            return x
        """)
    doc = json.loads(dump_report(lint_json_report(r)))
    validate_report(doc)
    assert doc["schema"] == SCHEMA_ID and doc["status"] == "findings"
    with pytest.raises(ValueError):
        validate_report({**doc, "extra": 1})


def test_metrics_diff_emits_the_same_schema(tmp_path, capsys):
    from tools.metrics_diff import main as mdiff_main
    base = tmp_path / "base.json"
    cand = tmp_path / "cand.json"
    base.write_text('{"serving": {"ttft_ms": 100.0}}')
    cand.write_text('{"serving": {"ttft_ms": 150.0}}')
    rc = mdiff_main([str(base), str(cand), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    validate_report(doc)
    assert rc == 1 and doc["tool"] == "metrics-diff"
    assert doc["findings"][0]["rule"] == "metric-regression"
    rc = mdiff_main([str(base), str(base), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    validate_report(doc)
    assert rc == 0 and doc["status"] == "ok"


# --------------------------------------------------------------- baselines

def test_baseline_fingerprints_survive_line_drift(tmp_path):
    src = """
        import jax

        @jax.jit
        def f(x, n):
            if n > 0:
                return x + n
            return x
        """
    r = lint_src(tmp_path, src)
    baseline = dump_baseline(r)
    # shift every line down: fingerprint is (rule, path, source line)
    (tmp_path / "mod.py").write_text(
        "# a new leading comment\n" + textwrap.dedent(src))
    r2 = run_lint([tmp_path / "mod.py"], root=tmp_path)
    assert r2.active
    matched = apply_baseline(r2, load_baseline(baseline))
    assert matched == 1 and not r2.active
    assert r2.suppressed[0].reason == "baseline"


def test_baseline_rejects_foreign_json():
    with pytest.raises(ValueError):
        load_baseline('{"something": "else"}')


# --------------------------------------------------------------------- CLI

def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x, n):\n"
                   "    if n > 0:\n        return x\n    return n\n")
    ok = tmp_path / "ok.py"
    ok.write_text("def g():\n    return 1\n")
    assert lint_main([str(ok), "--root", str(tmp_path)]) == 0
    assert lint_main([str(bad), "--root", str(tmp_path)]) == 1
    assert lint_main([str(tmp_path / "missing.py")]) == 2
    assert lint_main([str(ok), "--rules", "no-such-rule"]) == 2
    assert lint_main(["--list-rules"]) == 0
    capsys.readouterr()
    assert lint_main([str(bad), "--root", str(tmp_path),
                      "--format", "json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    validate_report(doc)
    assert doc["tool"] == "dla-lint" and doc["summary"]["findings"] == 1


def test_cli_write_then_apply_baseline(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x, n):\n"
                   "    if n > 0:\n        return x\n    return n\n")
    base = tmp_path / "baseline.json"
    assert lint_main([str(bad), "--root", str(tmp_path),
                      "--write-baseline", str(base)]) == 0
    assert lint_main([str(bad), "--root", str(tmp_path),
                      "--baseline", str(base)]) == 0
    assert lint_main([str(bad), "--root", str(tmp_path),
                      "--baseline", str(tmp_path / "nope.json")]) == 2


# ------------------------------------------ unsynchronized-shared-state

def test_shared_state_fires_across_thread_roles(tmp_path):
    r = lint_src(tmp_path, """
        import threading

        class Pipe:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0
                self._t = threading.Thread(
                    target=self._worker, name="dla-pipe-worker", daemon=True)
                self._t.start()

            def _worker(self):
                while True:
                    self._count += 1

            def read(self):
                return self._count
        """)
    assert "unsynchronized-shared-state" in fired(r)


def test_shared_state_silent_with_common_lock(tmp_path):
    r = lint_src(tmp_path, """
        import threading

        class Pipe:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0
                self._t = threading.Thread(
                    target=self._worker, name="dla-pipe-worker", daemon=True)
                self._t.start()

            def _worker(self):
                while True:
                    with self._lock:
                        self._count += 1

            def read(self):
                with self._lock:
                    return self._count
        """)
    assert "unsynchronized-shared-state" not in fired(r)


def test_thread_roles_propagate_to_spawn_targets(tmp_path):
    from dla_tpu.analysis.core import collect_files
    from dla_tpu.analysis.threads import get_model
    p = tmp_path / "m.py"
    p.write_text(textwrap.dedent("""
        import threading

        class Pipe:
            def __init__(self):
                self._t = threading.Thread(
                    target=self._worker, name="dla-pipe-worker")

            def _worker(self):
                self._tick()

            def _tick(self):
                pass

            def read(self):
                return 1
        """))
    model = get_model(collect_files([p], root=tmp_path))
    assert model.roles_of("m.py::Pipe._worker") == {"dla-pipe-worker"}
    assert model.roles_of("m.py::Pipe._tick") == {"dla-pipe-worker"}
    assert "main" in model.roles_of("m.py::Pipe.read")


# ----------------------------------------------- lock-order-inversion

def test_lock_order_inversion_fires_on_cycle_via_call_chain(tmp_path):
    r = lint_src(tmp_path, """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._b:
                    self._locked_a()

            def _locked_a(self):
                with self._a:
                    pass
        """)
    assert "lock-order-inversion" in fired(r)


def test_lock_order_silent_with_consistent_order(tmp_path):
    r = lint_src(tmp_path, """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._a:
                    with self._b:
                        pass
        """)
    assert "lock-order-inversion" not in fired(r)


# ----------------------------------------------- blocking-under-lock

def test_blocking_under_lock_fires_on_sleep(tmp_path):
    r = lint_src(tmp_path, """
        import threading
        import time

        _lock = threading.Lock()

        def heartbeat():
            with _lock:
                time.sleep(0.5)
        """)
    assert "blocking-under-lock" in fired(r)


def test_blocking_under_lock_silent_outside_region(tmp_path):
    r = lint_src(tmp_path, """
        import threading
        import time

        _lock = threading.Lock()
        _beats = []

        def heartbeat():
            time.sleep(0.5)
            with _lock:
                _beats.append(1)
        """)
    assert "blocking-under-lock" not in fired(r)


# --------------------------------------------- conditional-collective

def test_conditional_collective_fires_on_rank_gated_barrier(tmp_path):
    r = lint_src(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def publish(step):
            if jax.process_index() == 0:
                multihost_utils.sync_global_devices("publish")
        """)
    assert "conditional-collective" in fired(r)


def test_conditional_collective_silent_when_hoisted(tmp_path):
    r = lint_src(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def publish(step, manifest):
            if jax.process_index() == 0:
                manifest.write_text("ok")
            multihost_utils.sync_global_devices("publish")
        """)
    assert "conditional-collective" not in fired(r)


# ---------------------------------------------------- thread naming policy

def test_every_repo_spawn_site_is_dla_named():
    """Every thread/timer/executor the repo spawns carries an explicit
    dla- prefixed name, so `py-spy`/`gdb` dumps and the lock witness
    attribute work to a subsystem by name alone."""
    from dla_tpu.analysis.core import collect_files
    from dla_tpu.analysis.threads import get_model
    model = get_model(collect_files(["dla_tpu", "tools"], root=REPO))
    spawns = [s for s in model.spawns
              if s.kind in ("thread", "timer", "executor")]
    assert len(spawns) >= 7, "expected the repo's known spawn sites"
    bad = sorted(f"{s.rel}:{s.line} name={s.name_source!r}"
                 for s in spawns
                 if not (s.name_source or "").startswith("dla-"))
    assert not bad, "spawn sites without a dla- thread name:\n" \
        + "\n".join(bad)


# ----------------------------------------------------- the repo lints clean

def test_repo_lints_clean_with_documented_suppressions():
    t0 = time.perf_counter()
    result = run_lint(["dla_tpu", "tools", "bench.py", "config"], root=REPO)
    elapsed = time.perf_counter() - t0
    assert not result.active, "unsuppressed findings:\n" + "\n".join(
        f"  {f.path}:{f.line}: [{f.rule}] {f.message}"
        for f in result.active)
    # every deliberate exception documents WHY it is allowed
    for f in result.suppressed:
        assert f.reason and f.reason.strip(), (
            f"{f.path}:{f.line}: suppression without a reason")
    assert elapsed < 10.0, f"lint took {elapsed:.1f}s (bound: 10s)"
