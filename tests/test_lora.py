"""LoRA: functional implementation of the reference's advertised-but-dead
model.lora surface (reference base_model.py:45-49 ``freeze_except_lora``
never called; config/distill_config.yaml:10-14; SURVEY.md sec 2.5).

Contract: zero-init B means adapters start as an exact no-op; training
moves only the adapter tree; merge_lora folds adapters into base weights
that reproduce the adapted forward; the SFT trainer wires it all from the
reference's ``model.lora: {enabled, r, alpha, dropout}`` block.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import Transformer


@pytest.fixture(scope="module")
def lora_model():
    cfg = get_model_config("tiny", lora_r=4, lora_alpha=8.0)
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    adapters = model.init_lora(jax.random.key(1))
    return model, params, adapters


def _batch(cfg, b=2, t=16, seed=0):
    rs = np.random.RandomState(seed)
    ids = jnp.asarray(rs.randint(1, cfg.vocab_size, (b, t)), jnp.int32)
    return ids, jnp.ones((b, t), jnp.int32)


def test_lora_init_is_identity(lora_model):
    """B = 0 at init => adapted forward == base forward exactly."""
    model, params, adapters = lora_model
    ids, mask = _batch(model.cfg)
    base = model.apply(params, ids, attention_mask=mask)
    adapted = model.apply(params, ids, attention_mask=mask, lora=adapters)
    np.testing.assert_allclose(np.asarray(adapted), np.asarray(base),
                               atol=1e-6)


def test_lora_param_count(lora_model):
    model, params, adapters = lora_model
    n_adapt = sum(int(l.size) for l in jax.tree.leaves(adapters))
    n_base = sum(int(l.size) for l in jax.tree.leaves(params))
    assert n_adapt < n_base / 10
    cfg = model.cfg
    dh = cfg.head_dim_
    qd, kvd = cfg.num_heads * dh, cfg.num_kv_heads * dh
    expected = cfg.num_layers * cfg.lora_r * (
        (cfg.hidden_size + qd)          # wq: A [D,r] + B [r,qd]
        + 2 * (cfg.hidden_size + kvd)   # wk, wv
        + (qd + cfg.hidden_size))       # wo
    assert n_adapt == expected


def test_lora_changes_forward_after_update(lora_model):
    """Perturbed B changes logits; base params untouched by construction."""
    model, params, adapters = lora_model
    ids, mask = _batch(model.cfg)
    moved = jax.tree.map(lambda x: x + 0.01, adapters)
    base = model.apply(params, ids, attention_mask=mask)
    adapted = model.apply(params, ids, attention_mask=mask, lora=moved)
    assert np.abs(np.asarray(adapted) - np.asarray(base)).max() > 1e-4


def test_merge_lora_matches_adapted_forward(lora_model):
    model, params, adapters = lora_model
    moved = jax.tree.map(
        lambda x: x + 0.02 * jnp.ones_like(x), adapters)
    ids, mask = _batch(model.cfg, seed=3)
    adapted = model.apply(params, ids, attention_mask=mask, lora=moved)
    merged = model.merge_lora(params, moved)
    folded = model.apply(merged, ids, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(folded), np.asarray(adapted),
                               rtol=2e-4, atol=2e-4)


def test_lora_gradients_flow_only_through_adapters(lora_model):
    model, params, adapters = lora_model

    def loss(ad):
        ids, mask = _batch(model.cfg, seed=5)
        logits = model.apply(params, ids, attention_mask=mask, lora=ad)
        return (logits.astype(jnp.float32) ** 2).mean()

    g = jax.grad(loss)(adapters)
    # A-grads are nonzero only through B != 0; at zero-B only B gets grads
    gb = g["layers"]["wq_lora_b"]
    assert float(jnp.abs(gb).max()) > 0


def test_sft_trainer_lora_loss_falls(mesh8):
    """End-to-end: reference-shaped model.lora config block drives an SFT
    trainer whose trainable tree is adapters only, and the loss falls."""
    from dla_tpu.training.train_sft import build_trainer

    config = {
        "experiment_name": "lora_sft_test",
        "model": {"model_name_or_path": "tiny", "tokenizer": "byte",
                  "lora": {"enabled": True, "r": 4, "alpha": 8,
                           "dropout": 0.0}},
        "optimization": {"total_batch_size": 8, "micro_batch_size": 2,
                         "learning_rate": 1e-2, "max_train_steps": 30,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": "/tmp/lora_sft_test", "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    rng = jax.random.key(0)
    with jax.sharding.set_mesh(mesh8):
        trainer, bundle = build_trainer(config, mesh8, rng)
        assert trainer.frozen is not None
        n_trainable = sum(int(l.size) for l in jax.tree.leaves(trainer.params))
        n_frozen = sum(int(l.size) for l in jax.tree.leaves(trainer.frozen))
        assert n_trainable < n_frozen / 10

        rs = np.random.RandomState(0)
        batch = {
            "input_ids": rs.randint(
                1, bundle.config.vocab_size, (8, 32)).astype(np.int32),
            "attention_mask": np.ones((8, 32), np.int32),
            "labels": rs.randint(
                1, bundle.config.vocab_size, (8, 32)).astype(np.int32),
        }
        first, losses = None, []
        for i in range(30):
            loss, _ = trainer.step_on_batch(batch, jax.random.fold_in(rng, i))
            losses.append(loss)
            first = first if first is not None else loss
        # rank-4 adapters memorizing random labels: expect a clear but
        # modest drop (full-rank training would collapse the loss)
        assert losses[-1] < first - 0.15, (first, losses[-1])


def test_resume_skips_merged_final_artifact(mesh8, tmp_path):
    """After a LoRA run writes its merged export (params-only, tag
    `merged`), `latest` names it — resume must fall back to the newest
    adapter training checkpoint instead of crashing on the mismatched
    tree."""
    from dla_tpu.training.model_io import (
        load_causal_lm, save_merged_lora_final)
    from dla_tpu.training.train_sft import build_trainer

    config = {
        "experiment_name": "lora_resume_test",
        "model": {"model_name_or_path": "tiny", "tokenizer": "byte",
                  "lora": {"enabled": True, "r": 2, "alpha": 4}},
        "optimization": {"total_batch_size": 4, "micro_batch_size": 1,
                         "learning_rate": 1e-3, "max_train_steps": 4,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": str(tmp_path), "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    rng = jax.random.key(0)
    rs = np.random.RandomState(0)
    with jax.sharding.set_mesh(mesh8):
        trainer, bundle = build_trainer(config, mesh8, rng)
        batch = {
            "input_ids": rs.randint(
                1, bundle.config.vocab_size, (4, 16)).astype(np.int32),
            "attention_mask": np.ones((4, 16), np.int32),
            "labels": rs.randint(
                1, bundle.config.vocab_size, (4, 16)).astype(np.int32),
        }
        for i in range(2):
            trainer.step_on_batch(batch, jax.random.fold_in(rng, i))
        trainer.save()                       # adapter step checkpoint
        save_merged_lora_final(trainer, bundle, trainer.frozen)  # latest->merged

        trainer2, _ = build_trainer(config, mesh8, rng)
        aux = trainer2.try_resume()
        assert aux is not None and trainer2.step == 2
        # and the merged artifact chains: a fresh model loads from `latest`
        merged = load_causal_lm(str(tmp_path), {}, rng)
        assert merged.config.lora_r == 0


def test_lora_run_without_step_checkpoints_still_resumable(mesh8, tmp_path):
    """save_every_steps=0 run: the only full training state is `final`
    (adapters+opt_state). The merged export must not clobber it, and
    resume must find it through the `latest` -> merged indirection."""
    from dla_tpu.training.model_io import save_merged_lora_final
    from dla_tpu.training.train_sft import build_trainer

    config = {
        "experiment_name": "lora_final_only",
        "model": {"model_name_or_path": "tiny", "tokenizer": "byte",
                  "lora": {"enabled": True, "r": 2, "alpha": 4}},
        "optimization": {"total_batch_size": 4, "micro_batch_size": 1,
                         "learning_rate": 1e-3, "max_train_steps": 2,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": str(tmp_path), "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    rng = jax.random.key(0)
    rs = np.random.RandomState(0)
    with jax.sharding.set_mesh(mesh8):
        trainer, bundle = build_trainer(config, mesh8, rng)
        batch = {
            "input_ids": rs.randint(
                1, bundle.config.vocab_size, (4, 16)).astype(np.int32),
            "attention_mask": np.ones((4, 16), np.int32),
            "labels": rs.randint(
                1, bundle.config.vocab_size, (4, 16)).astype(np.int32),
        }
        trainer.step_on_batch(batch, rng)
        trainer.save(tag="final")            # end-of-fit training state
        save_merged_lora_final(trainer, bundle, trainer.frozen)

        trainer2, _ = build_trainer(config, mesh8, rng)
        aux = trainer2.try_resume()
        assert aux is not None and trainer2.step == 1


def test_rlhf_lora_rollout_update(mesh8):
    """RLHF with adapters: rollouts decode over the merged base+adapter
    tree, the reinforce update trains adapters only, and the frozen base
    doubles as the reference model (every phase now wires the reference's
    dead model.lora surface)."""
    from dla_tpu.generation.engine import GenerationConfig, build_generate_fn
    from dla_tpu.models.reward import RewardModel
    from dla_tpu.training.model_io import init_lora_adapters, load_causal_lm
    from dla_tpu.training.train_rlhf import (
        make_policy_gradient_loss,
        make_score_fn,
    )
    from dla_tpu.training.trainer import Trainer
    from dla_tpu.parallel.sharding import sharding_tree

    policy = load_causal_lm(
        "tiny", {"tokenizer": "byte",
                 "lora": {"enabled": True, "r": 4, "alpha": 8}},
        jax.random.key(0))
    adapters, lora_specs = init_lora_adapters(policy, jax.random.key(17))
    rm = RewardModel(policy.config)
    config = {
        "experiment_name": "lora_rlhf_test",
        "optimization": {"total_batch_size": 4, "micro_batch_size": 1,
                         "learning_rate": 1e-3, "max_train_steps": 4,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": "/tmp/lora_rlhf_test", "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    with jax.sharding.set_mesh(mesh8):
        trainer = Trainer(
            config=config, mesh=mesh8,
            loss_fn=make_policy_gradient_loss(policy.model, "reinforce",
                                              0.2, lora=True),
            params=adapters, param_specs=lora_specs,
            frozen={"base": policy.params},
            frozen_specs={"base": policy.specs})
        rm_params = jax.device_put(
            rm.init(jax.random.key(2)),
            sharding_tree(rm.partition_specs(), mesh8))
        gen = GenerationConfig(max_new_tokens=8, do_sample=True,
                               temperature=1.0, eos_token_id=-1,
                               pad_token_id=0)
        generate_fn = jax.jit(build_generate_fn(policy.model, gen))
        score_fn = make_score_fn(policy.model, policy.model, rm)
        merge_fn = jax.jit(policy.model.merge_lora)

        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(1, 100, (8, 8)), jnp.int32)
        mask = jnp.ones((8, 8), jnp.int32)
        for i in range(2):
            rp = merge_fn(trainer.frozen["base"], trainer.params)
            out = generate_fn(rp, ids, mask, jax.random.key(i))
            scores = score_fn(rp, trainer.frozen["base"], rm_params,
                              out["sequences"], out["sequence_mask"],
                              jnp.float32(0.1))
            up = {"sequences": out["sequences"],
                  "sequence_mask": out["sequence_mask"],
                  "advantages": scores["advantages"],
                  "behavior_logp": scores["behavior_logp"]}
            loss, metrics = trainer.step_on_device_batch(
                up, jax.random.key(100 + i))
            assert np.isfinite(loss)
        # adapters moved; base untouched
        moved = sum(float(jnp.sum(jnp.abs(l)))
                    for l in jax.tree.leaves(trainer.params))
        assert moved > 0.0
        # on step 0 the merged tree equals the base (B adapters start 0),
        # so behavior_logp under merged == logp under base+adapters
        assert np.isfinite(float(jnp.mean(scores["behavior_logp"])))


def test_reward_trainer_lora_loss_falls_and_merges(mesh8, tmp_path):
    """Reward model with backbone adapters + full-rank head: pairwise
    loss falls, and the merged export loads back as a plain reward model
    scoring identically to the adapted one (the artifact RLHF chains)."""
    from dla_tpu.training.model_io import build_reward_model
    from dla_tpu.training.train_reward import make_reward_loss

    model_cfg = {"base_model_name_or_path": "tiny", "tokenizer": "byte",
                 "lora": {"enabled": True, "r": 4, "alpha": 8}}
    from dla_tpu.training.model_io import (
        init_lora_adapters,
        save_merged_lora_final,
    )
    from dla_tpu.training.trainer import Trainer

    bundle = build_reward_model(model_cfg, jax.random.key(0))
    head = bundle.params.pop("reward_head")
    head_spec = bundle.specs.pop("reward_head")
    adapters, lora_specs = init_lora_adapters(bundle, jax.random.key(17))
    config = {
        "experiment_name": "lora_rm_test",
        "optimization": {"total_batch_size": 8, "micro_batch_size": 2,
                         "learning_rate": 1e-2, "max_train_steps": 30,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": str(tmp_path / "ckpt"), "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    with jax.sharding.set_mesh(mesh8):
        trainer = Trainer(
            config=config, mesh=mesh8,
            loss_fn=make_reward_loss(bundle.model, lora=True),
            params={"lora": adapters, "reward_head": head},
            param_specs={"lora": lora_specs, "reward_head": head_spec},
            frozen=bundle.params, frozen_specs=bundle.specs)

        def sub(seed):
            r = np.random.RandomState(seed)
            return {"input_ids": r.randint(1, 100, (8, 16)).astype(np.int32),
                    "attention_mask": np.ones((8, 16), np.int32)}

        batch = {"chosen": sub(1), "rejected": sub(2)}
        losses = []
        for i in range(30):
            loss, _ = trainer.step_on_batch(
                batch, jax.random.fold_in(jax.random.key(0), i))
            losses.append(loss)
        assert losses[-1] < losses[0] - 0.05, (losses[0], losses[-1])

        save_merged_lora_final(trainer, bundle, trainer.frozen, "byte")
        # chained load: plain reward model (lora_r=0 in merged aux)
        merged = build_reward_model(
            {"base_model_name_or_path": str(tmp_path / "ckpt" / "latest"),
             "tokenizer": "byte"}, jax.random.key(9))
        assert merged.config.lora_r == 0
        ids = sub(1)
        want = bundle.model.apply(
            {**trainer.frozen, "reward_head": trainer.params["reward_head"]},
            jnp.asarray(ids["input_ids"]), jnp.asarray(ids["attention_mask"]),
            lora=trainer.params["lora"])
        got = merged.model.apply(
            merged.params, jnp.asarray(ids["input_ids"]),
            jnp.asarray(ids["attention_mask"]))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


def test_dpo_trainer_lora_loss_falls(mesh8):
    """DPO with adapters as the trainable tree: the frozen base doubles
    as the reference model (no duplicated ref weights), preference loss
    falls, and ref logps stay pinned to the base (round-2 verdict next
    -step 8 — unblocks 70B preference tuning without full Adam state)."""
    from dla_tpu.training.model_io import init_lora_adapters, load_causal_lm
    from dla_tpu.training.train_dpo import make_dpo_loss
    from dla_tpu.training.trainer import Trainer

    policy = load_causal_lm(
        "tiny", {"tokenizer": "byte",
                 "lora": {"enabled": True, "r": 4, "alpha": 8}},
        jax.random.key(0))
    adapters, lora_specs = init_lora_adapters(policy, jax.random.key(17))
    config = {
        "experiment_name": "lora_dpo_test",
        "optimization": {"total_batch_size": 8, "micro_batch_size": 2,
                         "learning_rate": 1e-2, "max_train_steps": 40,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": "/tmp/lora_dpo_test", "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    with jax.sharding.set_mesh(mesh8):
        trainer = Trainer(
            config=config, mesh=mesh8,
            loss_fn=make_dpo_loss(policy.model, policy.model, beta=0.1,
                                  lora=True),
            params=adapters, param_specs=lora_specs,
            frozen={"base": policy.params},
            frozen_specs={"base": policy.specs})
        def sub(seed):
            r = np.random.RandomState(seed)
            return {"input_ids": r.randint(1, 100, (8, 16)).astype(np.int32),
                    "attention_mask": np.ones((8, 16), np.int32)}

        batch = {"chosen": sub(1), "rejected": sub(2)}
        losses = []
        for i in range(40):
            loss, metrics = trainer.step_on_batch(
                batch, jax.random.fold_in(jax.random.key(0), i))
            losses.append(loss)
        # rank-4 adapters on a 2-layer model: expect a clear monotone-ish
        # drop from the 0.6931 start, not a collapse
        assert losses[-1] < losses[0] - 0.03, (losses[0], losses[-1])
        assert metrics["preference_rate"] > 0.9


def test_gemma2_lora_composition():
    """LoRA adapters over a gemma-2 base (4 norms, softcaps, alternating
    window): gradients flow, merged tree == base+adapter forward."""
    import dataclasses

    from dla_tpu.ops.fused_ce import model_fused_ce

    cfg = dataclasses.replace(
        get_model_config("tiny-gqa"),
        arch="gemma2", sliding_window=6, sliding_window_pattern=2,
        attn_logit_softcap=20.0, final_logit_softcap=10.0,
        query_pre_attn_scalar=8, tie_embeddings=True, lora_r=4)
    model = Transformer(cfg)
    base = model.init(jax.random.key(0))
    adapters = model.init_lora(jax.random.key(1))
    rs = np.random.RandomState(0)
    batch = {
        "input_ids": jnp.asarray(rs.randint(1, 100, (2, 16)), jnp.int32),
        "attention_mask": jnp.ones((2, 16), jnp.int32),
        "labels": jnp.asarray(rs.randint(1, 100, (2, 16)), jnp.int32),
    }

    def loss(ad):
        return model_fused_ce(model, base, batch, lora=ad)[0]

    grads = jax.grad(loss)(adapters)
    for g in jax.tree.leaves(grads):
        assert np.isfinite(np.asarray(g)).all()
    stepped = jax.tree.map(lambda a, g: a - 0.3 * g, adapters, grads)
    merged = model.merge_lora(base, stepped)
    out_m = model.apply(merged, batch["input_ids"])
    out_a = model.apply(base, batch["input_ids"], lora=stepped)
    np.testing.assert_allclose(np.asarray(out_m), np.asarray(out_a),
                               rtol=2e-4, atol=2e-5)


def test_interleaved_pipeline_lora_composition():
    """LoRA leaves merged into the layer stream survive the circular
    schedule's [L] -> [S, V, c] reshape: PP-interleave forward with
    adapters == no-mesh forward with adapters."""
    import dataclasses

    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.parallel.sharding import sharding_tree

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = dataclasses.replace(get_model_config("tiny-gqa"),
                              pipeline_interleave=2, lora_r=4)
    model = Transformer(cfg)
    base = model.init(jax.random.key(2))
    adapters = jax.tree.map(
        lambda a: a + 0.05, model.init_lora(jax.random.key(3)))
    rs = np.random.RandomState(4)
    ids = jnp.asarray(rs.randint(1, 100, (4, 16)), jnp.int32)

    want = model.apply(base, ids, lora=adapters)
    mesh = build_mesh(MeshConfig(stage=2, fsdp=2, model=2, sequence=1))
    with jax.sharding.set_mesh(mesh):
        sb = jax.device_put(base, sharding_tree(model.partition_specs(),
                                                mesh))
        sa = jax.device_put(adapters, sharding_tree(
            model.lora_partition_specs(), mesh))
        got = jax.jit(lambda p, a: model.apply(p, ids, lora=a))(sb, sa)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-4)
