"""Cross-host federation tests (serving/federation.py): gossip-beat
discovery with TTL staleness, placement over gateway-fronted fleets
that stays BIT-IDENTICAL — greedy and explicitly-seeded — to the
in-process FleetRouter on the same trace, journal replay under ``net=``
wire chaos with zero lost requests, mid-stream MigrationTicket handoff
over the wire, and the cross-process acceptance run: two subprocess
gateway-fronted fleets behind a FederatedRouter reproduce the
in-process streams exactly, and killing one fleet MID-STREAM loses
nothing (orphaned streams re-place and replay bit-identically)."""
import signal
import subprocess
import sys
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from dla_tpu.resilience.faults import FaultPlan
from dla_tpu.serving import (
    FederatedRouter,
    FederationConfig,
    FleetConfig,
    FleetRouter,
    GossipBeater,
    SamplingParams,
    ServingConfig,
    ServingEngine,
    ServingGateway,
)

REPO_ROOT = Path(__file__).resolve().parent.parent
MAX_NEW = 4
PAGE = 4
SEEDED = dict(temperature=0.9, top_p=0.95, top_k=0, seed=77,
              do_sample=True)


@pytest.fixture(scope="module")
def serve_setup():
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    gen = GenerationConfig(max_new_tokens=16, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    return model, params, gen


def _factory(serve_setup):
    model, params, gen = serve_setup

    def factory(slot):
        return ServingEngine(model, params, gen, ServingConfig(
            page_size=PAGE, num_pages=64, num_slots=2, max_model_len=32,
            max_prefill_batch=2, prefill_chunk=PAGE, prefix_cache=True,
            fault_plan=""))
    return factory


def _prompts(families=3, per_family=3, seed=11):
    rs = np.random.RandomState(seed)
    prompts = []
    for _ in range(families):
        head = [int(t) for t in rs.randint(3, 500, (PAGE,))]
        for _ in range(per_family):
            prompts.append(head + [int(t)
                                   for t in rs.randint(3, 500, (2,))])
    return prompts


def _reference(serve_setup, prompts, new_tokens=MAX_NEW, sampling=None):
    """In-process FleetRouter outputs for the same trace — the streams
    federation must reproduce over the wire."""
    router = FleetRouter(_factory(serve_setup), FleetConfig(engines=2))
    params = ([None] * len(prompts) if sampling is None
              else [SamplingParams(**sampling)] * len(prompts))
    rids = [router.submit(p, new_tokens, sampling=s)
            for p, s in zip(prompts, params)]
    results = router.run_until_drained(max_steps=5000)
    return [list(results[r].generated) for r in rids]


def _wait_live(fed, n, timeout_s=300.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if len(fed.live_peers()) >= n:
            return
        time.sleep(0.05)
    pytest.fail(f"never saw {n} live peers; have {fed.live_peers()}")


# ---------------------------------------------------------------------------
# in-process (gateways + router all in this process)
# ---------------------------------------------------------------------------

def test_gossip_discovery_and_ttl_staleness(serve_setup, tmp_path):
    cfg = FederationConfig(lease_ttl_s=0.6, beat_interval_s=0.1)
    gw = ServingGateway(_factory(serve_setup)(0))
    beater = GossipBeater(gw, tmp_path, "solo", cfg)
    fed = FederatedRouter(tmp_path, cfg)
    try:
        _wait_live(fed, 1, timeout_s=30)
        peer = fed.live_peers()[0]
        assert peer["name"] == "solo"
        assert peer["url"] == gw.url
        assert fed.metrics.snapshot()[
            "serving/federation/gossip_beats"] >= 1
        # stop the heartbeat: the peer goes stale one TTL later and is
        # never placed on again (counted, not crashed on)
        beater.stop()
        time.sleep(cfg.lease_ttl_s + 0.3)
        assert fed.live_peers() == []
        assert fed.metrics.snapshot()[
            "serving/federation/stale_peers"] >= 1
    finally:
        beater.stop()
        gw.close()


def test_federated_streams_bit_identical_to_fleet(serve_setup, tmp_path):
    prompts = _prompts()
    ref_greedy = _reference(serve_setup, prompts)
    ref_seeded = _reference(serve_setup, prompts, sampling=SEEDED)

    factory = _factory(serve_setup)
    gws = [ServingGateway(FleetRouter(factory, FleetConfig(engines=2)))
           for _ in range(2)]
    beaters = [GossipBeater(g, tmp_path, n) for g, n in zip(gws, "ab")]
    fed = FederatedRouter(tmp_path, FederationConfig())
    try:
        _wait_live(fed, 2)
        fids = [fed.submit(p, MAX_NEW) for p in prompts]
        out = fed.results(timeout_s=300)
        assert [out[f].tokens for f in fids] == ref_greedy
        assert all(out[f].state == "finished" for f in fids)
        assert fed.requests_lost == 0
        # per-request fold_in(seed, k) sampling is peer-independent, so
        # an EXPLICIT seed is bit-identical across hosts too
        fids = [fed.submit(p, MAX_NEW, sampling=SEEDED)
                for p in prompts]
        out = fed.results(timeout_s=300)
        assert [out[f].tokens for f in fids] == ref_seeded
        snap = fed.metrics.snapshot()
        assert snap["serving/federation/routed_remote"] == \
            2 * len(prompts)
        assert snap["serving/federation/stale_peers"] == 0
    finally:
        for b in beaters:
            b.stop()
        for g in gws:
            g.close()


def test_net_chaos_replays_with_zero_loss(serve_setup, tmp_path):
    prompts = _prompts()
    ref = _reference(serve_setup, prompts)
    factory = _factory(serve_setup)
    gws = [ServingGateway(FleetRouter(factory, FleetConfig(engines=2)))
           for _ in range(2)]
    beaters = [GossipBeater(g, tmp_path, n) for g, n in zip(gws, "ab")]
    plan = FaultPlan.parse("net=3:delay:0.01;net=5:drop;net=8:disconnect")
    fed = FederatedRouter(tmp_path, FederationConfig(), fault_plan=plan)
    try:
        _wait_live(fed, 2)
        fids = [fed.submit(p, MAX_NEW) for p in prompts]
        out = fed.results(timeout_s=300)
        # a dropped op and a torn stream each cost a replay, never a
        # request — and the replayed stream is the SAME stream
        assert [out[f].tokens for f in fids] == ref
        assert fed.requests_lost == 0
        assert fed.replayed >= 1
        assert not plan.pending()      # every armed fault fired
    finally:
        for b in beaters:
            b.stop()
        for g in gws:
            g.close()


def test_migrate_midstream_over_wire_bit_identical(serve_setup,
                                                   tmp_path):
    prompt = _prompts(families=1, per_family=1, seed=3)[0]
    ref = _reference(serve_setup, [prompt], new_tokens=10)[0]
    factory = _factory(serve_setup)
    slow = FleetRouter(factory, FleetConfig(engines=1))
    orig_step = slow.step

    def slow_step():
        time.sleep(0.06)     # keep the stream open long enough to move
        return orig_step()
    slow.step = slow.poll = slow_step
    gw_a = ServingGateway(slow)
    gw_b = ServingGateway(FleetRouter(factory, FleetConfig(engines=1)))
    beaters = [GossipBeater(gw_a, tmp_path, "a"),
               GossipBeater(gw_b, tmp_path, "b")]
    fed = FederatedRouter(tmp_path, FederationConfig())
    try:
        _wait_live(fed, 2)
        fed.results(timeout_s=300)
        # catch a request mid-stream on the slow peer, then ship it —
        # serialized KV ticket out of a, installed into b, stream
        # re-attached with a catch-up — and the total stream must be
        # what it would have been had it never moved
        fid = None
        for _ in range(6):
            f = fed.submit(prompt, 10)
            fr = fed._requests[f]
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if fr.peer == "a" and fr.remote_rid is not None \
                        and len(fr.tokens) >= 2 and fr.state == "pending":
                    fid = f
                    break
                if fr.state != "pending":
                    break
                time.sleep(0.01)
            if fid is not None:
                break
            fed.results(timeout_s=300)
        assert fid is not None, "never caught a mid-stream request"
        fed.migrate(fid, "b")
        out = fed.results(timeout_s=300)[fid]
        assert out.state == "finished"
        assert out.peer == "b"
        assert out.tokens == ref
        assert fed.requests_lost == 0
        assert fed.metrics.snapshot()[
            "serving/federation/handoff_bytes"] > 0
    finally:
        for b in beaters:
            b.stop()
        gw_a.close()
        gw_b.close()


# ---------------------------------------------------------------------------
# cross-process acceptance: two subprocess fleets behind the router
# ---------------------------------------------------------------------------

def test_cross_process_fleets_bit_identical_and_kill_safe(
        serve_setup, tmp_path):
    """The ISSUE's acceptance bar, one launch, two phases: (1) the same
    seeded trace through two SUBPROCESS gateway-fronted fleets produces
    token streams bit-identical to the in-process FleetRouter — greedy
    AND explicitly-seeded; (2) SIGKILL one fleet mid-trace and nothing
    is lost — orphaned streams re-place on the survivor and replay to
    the same tokens."""
    sys.path.insert(0, str(REPO_ROOT))
    from _cpuhost import scrubbed_cpu_env

    prompts = _prompts()
    ref_greedy = _reference(serve_setup, prompts, new_tokens=8)
    ref_seeded = _reference(serve_setup, prompts, new_tokens=8,
                            sampling=SEEDED)

    env = scrubbed_cpu_env(1, str(REPO_ROOT))
    procs = {}
    fed = FederatedRouter(tmp_path, FederationConfig())
    try:
        for name in ("a", "b"):
            procs[name] = subprocess.Popen(
                [sys.executable,
                 str(REPO_ROOT / "tests" / "_gateway_worker.py"),
                 str(tmp_path), name, "25"],
                env=env, cwd=str(REPO_ROOT),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
        _wait_live(fed, 2, timeout_s=600)

        # phase 1: wire == in-process, greedy and seeded
        fids = [fed.submit(p, 8) for p in prompts]
        out = fed.results(timeout_s=600)
        assert [out[f].tokens for f in fids] == ref_greedy
        fids = [fed.submit(p, 8, sampling=SEEDED) for p in prompts]
        out = fed.results(timeout_s=600)
        assert [out[f].tokens for f in fids] == ref_seeded
        assert fed.requests_lost == 0

        # phase 2: kill one fleet MID-STREAM
        fids = [fed.submit(p, 8) for p in prompts]
        victim = None
        deadline = time.monotonic() + 300
        while victim is None and time.monotonic() < deadline:
            for f in fids:
                fr = fed._requests[f]
                if fr.state == "pending" and fr.peer in procs \
                        and len(fr.tokens) >= 1:
                    victim = fr.peer
                    break
            time.sleep(0.01)
        assert victim is not None, "no request was caught mid-stream"
        procs[victim].send_signal(signal.SIGKILL)
        out = fed.results(timeout_s=600)
        assert [out[f].tokens for f in fids] == ref_greedy
        assert all(out[f].state == "finished" for f in fids)
        assert fed.requests_lost == 0
        assert fed.replayed >= 1
    finally:
        for p in procs.values():
            p.kill()
        for p in procs.values():
            try:
                p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                pass
