"""Fleet-router tests: cache-aware placement (peek + sticky-prefix
affinity) keeps a routed N-engine fleet BIT-IDENTICAL to a single
engine on the same trace — greedy and explicitly-seeded sampled, and
with one member under chaos — while scale-down drains through the
existing draining contract with zero lost requests (queued work
rebalanced to peers with rid/sampling state intact), the autoscaler
grows and shrinks the fleet on the pressure signal, capped drains shed
stragglers to a terminal state, and ``serving/fleet/*`` counters live
in the router's registry so member rebuilds never reset them."""
import jax
import numpy as np
import pytest

from dla_tpu.serving import (
    TERMINAL_STATES,
    FleetConfig,
    FleetRouter,
    RequestState,
    SamplingParams,
    ServingConfig,
    ServingEngine,
    SupervisorConfig,
)

MAX_NEW = 4
FAMILIES = 4
PER_FAMILY = 6
PAGE = 4


@pytest.fixture(scope="module")
def serve_setup():
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    return model, params, gen


def _factory(serve_setup, **cfg_kw):
    """-> factory(slot) for FleetRouter; also builds the single-engine
    baseline via factory(0). fault_plan="" (not None) pins members
    fault-free even when $DLA_FAULT_PLAN is set in the environment."""
    model, params, gen = serve_setup
    kw = dict(page_size=PAGE, num_pages=64, num_slots=2,
              max_model_len=32, max_prefill_batch=2, prefill_chunk=PAGE,
              prefix_cache=True, fault_plan="")
    kw.update(cfg_kw)

    def factory(slot):
        return ServingEngine(model, params, gen, ServingConfig(**kw))
    return factory


def _shared_prefix_prompts(families=FAMILIES, per_family=PER_FAMILY,
                           seed=11):
    # uniform length (one full page head + 2-token suffix): a single
    # prefill bucket, so chaos-arm rebuild compiles never land inside
    # a watchdog window
    rs = np.random.RandomState(seed)
    prompts = []
    for _ in range(families):
        head = [int(t) for t in rs.randint(3, 500, (PAGE,))]
        for _ in range(per_family):
            prompts.append(head + [int(t)
                                   for t in rs.randint(3, 500, (2,))])
    return prompts


def _serve(eng, prompts, sampling=None):
    """Outputs of THIS call in submission order; engine-shaped: works
    identically on a bare ServingEngine and a FleetRouter."""
    params = sampling or [None] * len(prompts)
    rids = [eng.submit(p, MAX_NEW, sampling=s)
            for p, s in zip(prompts, params)]
    results = eng.run_until_drained(max_steps=5000)
    assert all(results[r].state in TERMINAL_STATES for r in rids)
    return [list(results[r].generated) for r in rids]


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------

def test_fleet_config_from_config_and_validation():
    assert FleetConfig.from_config(None) is None
    assert FleetConfig.from_config({"enabled": False}) is None
    cfg = FleetConfig.from_config({"engines": 3, "placement": "random"})
    assert cfg.engines == 3 and cfg.placement == "random"
    with pytest.raises(ValueError, match="unknown fleet config"):
        FleetConfig.from_config({"engine_count": 3})
    with pytest.raises(ValueError, match="placement"):
        FleetConfig(placement="sticky")
    with pytest.raises(ValueError):
        FleetConfig(engines=5, max_engines=4)


# ---------------------------------------------------------------------------
# placement-independence: the core bit-identity guarantee
# ---------------------------------------------------------------------------

def test_fleet_greedy_bit_identical_to_single_engine(serve_setup):
    """A routed N=4 fleet emits exactly the single engine's tokens on
    the same shared-prefix trace, and placement actually engages: most
    requests route by prefix (peek hit or sticky affinity), spread
    over more than one member."""
    factory = _factory(serve_setup)
    prompts = _shared_prefix_prompts()

    single = factory(0)
    want = _serve(single, prompts)
    single.close()

    router = FleetRouter(factory, FleetConfig(engines=4))
    got = _serve(router, prompts)
    snap = router.fleet_snapshot()
    placed_slots = {m.slot for m in router._placement.values()}
    router.close()

    assert got == want
    assert snap["serving/fleet/engines_active"] == 4
    assert (snap["serving/fleet/routed_by_prefix"]
            + snap["serving/fleet/routed_by_load"]) == len(prompts)
    # sticky affinity must dominate a burst-submitted shared-prefix mix
    assert snap["serving/fleet/routed_by_prefix"] > len(prompts) / 2
    assert len(placed_slots) > 1          # it is actually a fleet


def test_fleet_seeded_sampling_bit_identical(serve_setup):
    """Sampled outputs are placement-independent too: token k is a pure
    function of (seed, k), so explicit per-request seeds give the same
    streams no matter which member decodes them."""
    factory = _factory(serve_setup)
    prompts = _shared_prefix_prompts(families=2, per_family=4)
    sampling = [SamplingParams(seed=1000 + i, temperature=0.8)
                for i in range(len(prompts))]

    single = factory(0)
    want = _serve(single, prompts, sampling)
    single.close()

    router = FleetRouter(factory, FleetConfig(engines=4))
    got = _serve(router, prompts, sampling)
    router.close()

    assert got == want


def test_fleet_random_placement_same_outputs(serve_setup):
    """The control arm: random placement scatters families (worse hit
    rate) but the emitted tokens are still identical — proof the router
    never lets placement leak into results."""
    factory = _factory(serve_setup)
    prompts = _shared_prefix_prompts(families=2, per_family=4)

    single = factory(0)
    want = _serve(single, prompts)
    single.close()

    router = FleetRouter(factory, FleetConfig(engines=3,
                                              placement="random"))
    got = _serve(router, prompts)
    router.close()
    assert got == want


# ---------------------------------------------------------------------------
# chaos: one member faulting must not change fleet output
# ---------------------------------------------------------------------------

def test_fleet_single_member_chaos_bit_identical_zero_loss(serve_setup):
    """Member 0 wedges (watchdog restart) and then raises a device
    error (supervised rebuild + replay); the router keeps the rest of
    the fleet serving. Every request reaches a terminal state and the
    outputs equal the fault-free fleet run — and the fleet counters,
    living in the router's registry, survive the member rebuilds."""
    clean_factory = _factory(serve_setup)
    chaos_engine = _factory(
        serve_setup,
        fault_plan="engine_step=2:wedge:0.3;engine_step=4:device_error")

    def chaos_factory(slot):
        return chaos_engine(slot) if slot == 0 else clean_factory(slot)

    sup_cfg = SupervisorConfig(watchdog_timeout_s=0.05,
                               watchdog_poll_s=0.01, max_restarts=3)
    prompts = _shared_prefix_prompts()
    fleet_cfg = FleetConfig(engines=3)

    clean = FleetRouter(clean_factory, fleet_cfg, supervisor=sup_cfg)
    want = _serve(clean, prompts)
    clean.close()

    router = FleetRouter(chaos_factory, fleet_cfg, supervisor=sup_cfg)
    got = _serve(router, prompts)
    snap = router.fleet_snapshot()
    restarts = [m.sup.restarts for m in router.members()]
    router.close()

    assert got == want
    assert restarts[0] >= 1 and restarts[1:] == [0, 0]
    # monotone across rebuilds: routing counters were incremented before
    # the faults fired and must still account for every admission
    assert (snap["serving/fleet/routed_by_prefix"]
            + snap["serving/fleet/routed_by_load"]) == len(prompts)
    assert snap["serving/fleet/engines_active"] == 3


# ---------------------------------------------------------------------------
# scaling: zero-loss drain, rebalance, autoscaler
# ---------------------------------------------------------------------------

def test_fleet_scale_down_rebalances_queued_zero_loss(serve_setup):
    """Retiring a member mid-burst moves its queued requests to peers
    (rid and streamed state preserved) and runs its in-flight work to
    completion: nothing is lost, outputs still match a single engine."""
    factory = _factory(serve_setup)
    prompts = _shared_prefix_prompts(families=2, per_family=6)

    single = factory(0)
    want = _serve(single, prompts)
    single.close()

    router = FleetRouter(factory, FleetConfig(engines=2))
    rids = [router.submit(p, MAX_NEW) for p in prompts]
    victim = router.members()[0]
    router.scale_down(victim)
    with pytest.raises(RuntimeError, match="last fleet member"):
        router.scale_down(router.members()[1])
    results = router.run_until_drained(max_steps=5000)
    snap = router.fleet_snapshot()
    got = [list(results[r].generated) for r in rids]
    remaining = router.members()
    router.close()

    assert all(results[r].state == RequestState.FINISHED for r in rids)
    assert got == want
    assert snap["serving/fleet/scale_downs"] == 1
    assert snap["serving/fleet/rebalanced_requests"] > 0
    assert snap["serving/fleet/engines_active"] == 1
    assert [m.slot for m in remaining] == [1]   # victim reclaimed


def test_fleet_autoscaler_grows_under_pressure_shrinks_idle(serve_setup):
    """Queue pressure above the threshold for ``patience`` checks adds
    members up to max_engines; a drained, idle fleet falls back to
    min_engines through the zero-loss retire path."""
    factory = _factory(serve_setup)
    cfg = FleetConfig(engines=1, min_engines=1, max_engines=3,
                      autoscale=True, scale_up_pressure=0.3,
                      scale_down_pressure=0.05, patience=2,
                      check_every=1)
    router = FleetRouter(factory, cfg)
    prompts = _shared_prefix_prompts(families=3, per_family=6)
    rids = [router.submit(p, MAX_NEW) for p in prompts]
    results = router.run_until_drained(max_steps=5000)
    snap_up = router.fleet_snapshot()
    assert all(results[r].state in TERMINAL_STATES for r in rids)
    # the fleet grew under the burst (it may already have begun
    # shrinking during the low-pressure tail of the drain — that is
    # the autoscaler working, not a miss)
    assert snap_up["serving/fleet/scale_ups"] >= 1

    for _ in range(60):                   # idle ticks: pressure ~ 0
        router.step()
        if router.num_engines == 1:
            break
    snap_down = router.fleet_snapshot()
    router.close()
    assert snap_down["serving/fleet/engines_active"] == 1
    assert snap_down["serving/fleet/scale_downs"] >= 1


def test_fleet_draining_rejects_admissions_then_drains(serve_setup):
    factory = _factory(serve_setup)
    router = FleetRouter(factory, FleetConfig(engines=2))
    prompts = _shared_prefix_prompts(families=1, per_family=3)
    rids = [router.submit(p, MAX_NEW) for p in prompts]
    router.begin_drain()
    with pytest.raises(RuntimeError, match="draining"):
        router.submit(prompts[0], MAX_NEW)
    results = router.drain(max_steps=5000)
    router.close()
    assert all(results[r].state == RequestState.FINISHED for r in rids)


# ---------------------------------------------------------------------------
# capped drain: stragglers shed, never stranded
# ---------------------------------------------------------------------------

def test_drain_on_cap_shed_resolves_stragglers(serve_setup):
    """run_until_drained(on_cap="shed") converts the old raise into a
    recorded disposition: every straggler reaches SHED, pages are
    released, and the flight recorder keeps the evidence."""
    eng = _factory(serve_setup)(0)
    prompts = _shared_prefix_prompts(families=1, per_family=4)
    rids = [eng.submit(p, MAX_NEW) for p in prompts]
    with pytest.raises(RuntimeError, match="did not drain"):
        eng.run_until_drained(max_steps=1)
    results = eng.run_until_drained(max_steps=1, on_cap="shed")
    assert all(results[r].state in TERMINAL_STATES for r in rids)
    assert any(results[r].state == RequestState.SHED for r in rids)
    assert eng.metrics.requests_shed.value > 0
    kinds = [e["kind"] for e in eng.recorder.events]
    assert "drain_cap" in kinds and "request_shed" in kinds
    eng.scheduler.assert_consistent()
    assert eng.cache.allocator.used_count == 0   # pages all released
    eng.close()


def test_fleet_drain_on_cap_shed(serve_setup):
    factory = _factory(serve_setup)
    router = FleetRouter(factory, FleetConfig(engines=2))
    prompts = _shared_prefix_prompts(families=2, per_family=3)
    rids = [router.submit(p, MAX_NEW) for p in prompts]
    results = router.run_until_drained(max_steps=1, on_cap="shed")
    router.close()
    assert all(results[r].state in TERMINAL_STATES for r in rids)
