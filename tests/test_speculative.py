"""Speculative decoding: draft proposes, target verifies a block in one
forward, acceptance keeps the target distribution exact. The load-
bearing invariants, all CPU-checkable without a trained draft:

- greedy spec decode == plain greedy decode EXACTLY, for ANY draft
  (acceptance only shortcuts serial steps, never changes tokens);
- a draft identical to the target accepts every proposal;
- EOS truncation and masks match the plain engine's semantics;
- column exhaustion (poor acceptance x alloc_factor) shortens rows but
  keeps the emitted region a correct prefix.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dla_tpu.generation.engine import GenerationConfig, build_generate_fn
from dla_tpu.generation.speculative import build_speculative_generate_fn
from dla_tpu.models.config import ModelConfig
from dla_tpu.models.transformer import Transformer


def _mk(seed, layers=2):
    cfg = ModelConfig(
        vocab_size=120, hidden_size=32, intermediate_size=64,
        num_layers=layers, num_heads=4, num_kv_heads=2,
        max_seq_length=128, attention="xla", remat="none",
        dtype="float32", param_dtype="float32")
    m = Transformer(cfg)
    return m, m.init(jax.random.key(seed))


@pytest.fixture(scope="module")
def models():
    target, tp = _mk(0)
    draft, dp = _mk(42, layers=1)
    return target, tp, draft, dp


def _prompts(b=3, t=9):
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(3, 110, (b, t)), jnp.int32)
    mask = jnp.ones((b, t), jnp.int32)
    mask = mask.at[b - 1, t - 2:].set(0)
    return ids, mask


def test_greedy_same_draft_bit_identical_and_all_accepted(models):
    target, tp, _, _ = models
    ids, mask = _prompts()
    gen = GenerationConfig(max_new_tokens=12, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    ref = jax.jit(build_generate_fn(target, gen))(
        tp, ids, mask, jax.random.key(1))
    out = jax.jit(build_speculative_generate_fn(
        target, target, gen, gamma=4))(
        tp, tp, ids, mask, jax.random.key(1))
    np.testing.assert_array_equal(np.asarray(ref["response_tokens"]),
                                  np.asarray(out["response_tokens"]))
    np.testing.assert_array_equal(np.asarray(ref["response_mask"]),
                                  np.asarray(out["response_mask"]))
    # a perfect draft accepts every proposal slot it is offered
    assert int(out["accepted_tokens"]) == int(out["proposal_slots"]) > 0


def test_greedy_any_draft_exact(models):
    """The killer invariant: with a RANDOM draft (different depth, never
    trained), greedy speculative output equals plain greedy output —
    fully, given enough cache columns."""
    target, tp, draft, dp = models
    ids, mask = _prompts()
    gen = GenerationConfig(max_new_tokens=12, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    ref = jax.jit(build_generate_fn(target, gen))(
        tp, ids, mask, jax.random.key(1))
    out = jax.jit(build_speculative_generate_fn(
        target, draft, gen, gamma=4, alloc_factor=4.0))(
        tp, dp, ids, mask, jax.random.key(1))
    np.testing.assert_array_equal(np.asarray(ref["response_tokens"]),
                                  np.asarray(out["response_tokens"]))
    np.testing.assert_array_equal(np.asarray(ref["response_mask"]),
                                  np.asarray(out["response_mask"]))


def test_column_exhaustion_yields_correct_prefix(models):
    """With a hostile draft and the default alloc_factor, rows may come
    back SHORT — but what is emitted must be a prefix-shaped mask whose
    tokens equal plain greedy's."""
    target, tp, draft, dp = models
    ids, mask = _prompts()
    n = 12
    gen = GenerationConfig(max_new_tokens=n, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    ref = jax.jit(build_generate_fn(target, gen))(
        tp, ids, mask, jax.random.key(1))
    out = jax.jit(build_speculative_generate_fn(
        target, draft, gen, gamma=4, alloc_factor=1.0))(
        tp, dp, ids, mask, jax.random.key(1))
    m = np.asarray(out["response_mask"]).astype(bool)
    rt = np.asarray(ref["response_tokens"])
    st = np.asarray(out["response_tokens"])
    assert (rt[m] == st[m]).all()
    for row in m:
        k = int(row.sum())
        assert row[:k].all() and not row[k:].any()  # prefix-shaped


def test_eos_truncates_like_plain_engine(models):
    """Pick an EOS id that plain greedy demonstrably emits mid-sequence;
    speculative greedy must truncate at the same position with the same
    mask."""
    target, tp, draft, dp = models
    ids, mask = _prompts()
    base = GenerationConfig(max_new_tokens=10, do_sample=False,
                            eos_token_id=-1, pad_token_id=0)
    probe = jax.jit(build_generate_fn(target, base))(
        tp, ids, mask, jax.random.key(1))
    eos = int(np.asarray(probe["response_tokens"])[0, 3])
    gen = GenerationConfig(max_new_tokens=10, do_sample=False,
                           eos_token_id=eos, pad_token_id=0)
    ref = jax.jit(build_generate_fn(target, gen))(
        tp, ids, mask, jax.random.key(1))
    out = jax.jit(build_speculative_generate_fn(
        target, draft, gen, gamma=3, alloc_factor=4.0))(
        tp, dp, ids, mask, jax.random.key(1))
    np.testing.assert_array_equal(np.asarray(ref["response_tokens"]),
                                  np.asarray(out["response_tokens"]))
    np.testing.assert_array_equal(np.asarray(ref["response_mask"]),
                                  np.asarray(out["response_mask"]))


def test_sampling_same_draft_accepts_everything(models):
    """With draft == target and do_sample, p == q so min(1, p/q) accepts
    every proposal; the output is a valid sampled stream (finite, in
    vocab, prefix-masked) and the telemetry shows full acceptance."""
    target, tp, _, _ = models
    ids, mask = _prompts()
    gen = GenerationConfig(max_new_tokens=12, do_sample=True,
                           temperature=0.9, top_p=0.9,
                           eos_token_id=-1, pad_token_id=0)
    out = jax.jit(build_speculative_generate_fn(
        target, target, gen, gamma=4))(
        tp, tp, ids, mask, jax.random.key(7))
    assert int(out["accepted_tokens"]) == int(out["proposal_slots"]) > 0
    toks = np.asarray(out["response_tokens"])
    m = np.asarray(out["response_mask"]).astype(bool)
    assert m.all()  # full acceptance delivers every requested token
    assert ((toks >= 0) & (toks < target.cfg.vocab_size)).all()


def test_sampling_divergent_draft_emits_valid_stream(models):
    """A random draft under sampling: acceptance is near zero, but the
    machinery must still emit an in-vocab prefix stream and telemetry
    must stay consistent (accepted <= proposals made)."""
    target, tp, draft, dp = models
    ids, mask = _prompts()
    gen = GenerationConfig(max_new_tokens=8, do_sample=True,
                           temperature=1.0, eos_token_id=-1,
                           pad_token_id=0)
    out = jax.jit(build_speculative_generate_fn(
        target, draft, gen, gamma=4, alloc_factor=4.0))(
        tp, dp, ids, mask, jax.random.key(9))
    assert 0 <= int(out["accepted_tokens"]) <= int(out["proposal_slots"])
    m = np.asarray(out["response_mask"]).astype(bool)
    toks = np.asarray(out["response_tokens"])
    assert ((toks[m] >= 0) & (toks[m] < target.cfg.vocab_size)).all()
    for row in m:
        k = int(row.sum())
        assert row[:k].all() and not row[k:].any()


def test_gamma_and_vocab_validation(models):
    target, tp, draft, dp = models
    gen = GenerationConfig(max_new_tokens=4)
    with pytest.raises(ValueError, match="gamma"):
        build_speculative_generate_fn(target, draft, gen, gamma=1)
    small, _ = _mk(3)
    import dataclasses
    bad = Transformer(dataclasses.replace(small.cfg, vocab_size=64))
    with pytest.raises(ValueError, match="vocab"):
        build_speculative_generate_fn(target, bad, gen, gamma=2)


def test_speculative_engine_generates_text(models):
    """SpeculativeEngine exposes GenerationEngine's generate_text
    surface (eval/teacher-gen batch loops take either): byte-tokenizer
    round trip produces decodable strings and telemetry."""
    from dla_tpu.data.tokenizers import ByteTokenizer
    from dla_tpu.generation.speculative import SpeculativeEngine

    target, tp, draft, dp = models
    tok = ByteTokenizer()
    gen = GenerationConfig(max_new_tokens=6, do_sample=True,
                           temperature=0.8)
    eng = SpeculativeEngine(target, draft, dp, tok, gen, gamma=3)
    texts, out = eng.generate_text(tp, ["hello", "spec decode"], 12,
                                   jax.random.key(0))
    assert len(texts) == 2 and all(isinstance(t, str) for t in texts)
    assert int(out["verify_rounds"]) >= 1


def test_greedy_exact_on_gemma2_style_target(models):
    """Speculative greedy exactness must survive the hardest arch
    composition: logit softcapping + alternating per-layer sliding
    windows (traced swa_on) in BOTH decode_step and decode_block."""
    _, _, draft, dp = models
    cfg = ModelConfig(
        vocab_size=120, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_length=128,
        attention="xla", remat="none", dtype="float32",
        param_dtype="float32", sliding_window=6,
        sliding_window_pattern=2, attn_logit_softcap=30.0)
    target = Transformer(cfg)
    tp = target.init(jax.random.key(8))
    ids, mask = _prompts()
    gen = GenerationConfig(max_new_tokens=10, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    ref = jax.jit(build_generate_fn(target, gen))(
        tp, ids, mask, jax.random.key(1))
    out = jax.jit(build_speculative_generate_fn(
        target, draft, gen, gamma=3, alloc_factor=4.0))(
        tp, dp, ids, mask, jax.random.key(1))
    np.testing.assert_array_equal(np.asarray(ref["response_tokens"]),
                                  np.asarray(out["response_tokens"]))
    np.testing.assert_array_equal(np.asarray(ref["response_mask"]),
                                  np.asarray(out["response_mask"]))


def test_done_rows_freeze_cache_lengths(models):
    """Regression: a row that hits EOS in an early round must FREEZE its
    target-cache length while stragglers keep running. Before the fix,
    done rows kept `1 + garbage_k` columns every spin of the verify
    loop, so their logical lengths grew with the batch-max round count —
    dragging any length-derived switch (rope scaling's original-context
    threshold) past what the row actually holds."""
    target, tp, draft, dp = models
    ids, mask = _prompts()
    base = GenerationConfig(max_new_tokens=24, do_sample=False,
                            eos_token_id=-1, pad_token_id=0)
    probe = jax.jit(build_generate_fn(target, base))(
        tp, ids, mask, jax.random.key(1))
    # an EOS row 0 demonstrably emits early; row 1+ may run much longer
    eos = int(np.asarray(probe["response_tokens"])[0, 2])
    gen = GenerationConfig(max_new_tokens=24, do_sample=False,
                           eos_token_id=eos, pad_token_id=0)
    gamma = 3
    out = jax.jit(build_speculative_generate_fn(
        target, draft, gen, gamma=gamma, alloc_factor=4.0))(
        tp, dp, ids, mask, jax.random.key(1))

    emitted = np.asarray(out["response_mask"]).sum(axis=1)
    cache_len = np.asarray(out["cache_lengths"])
    prompt_len = np.asarray(mask).sum(axis=1)
    rounds = int(out["verify_rounds"])
    # the scenario is real: row 0 finished early, the loop kept going
    assert emitted[0] < emitted.max()
    assert rounds >= 3

    # frozen: each row's cache length is bounded by what the row
    # actually holds (prompt + emitted + at most gamma in-flight
    # columns from its final live round), INDEPENDENT of how many
    # rounds the stragglers added. The broken version grew done rows
    # by >= 1 column per extra round.
    for i in range(len(emitted)):
        assert cache_len[i] <= prompt_len[i] + emitted[i] + gamma, \
            (i, cache_len[i], prompt_len[i], emitted[i], rounds)
    # and the early-finisher sits strictly below the straggler
    live = int(np.argmax(emitted))
    assert cache_len[0] < cache_len[live]
