"""Disaggregated RLHF rollout tests (dla_tpu/rollout): sync-mode bit
parity with the seeded ``build_generate_fn`` batch path, in-place
weight refit with pinned compile counters, async staleness bookkeeping
(stale-use + discard-regenerate), and mid-rollout supervisor restarts
replaying to bit-identical outputs."""
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.generation.engine import GenerationConfig, build_generate_fn
from dla_tpu.models.config import get_model_config
from dla_tpu.models.transformer import Transformer
from dla_tpu.ops.sampling import derive_rollout_seeds
from dla_tpu.rollout import (
    RolloutEngine,
    RolloutMetrics,
    WeightRefitter,
    apply_staleness_correction,
    build_rollout_pipeline,
    make_staleness_corrector,
)
from dla_tpu.serving.server import ServingConfig

MAX_NEW = 5


@pytest.fixture(scope="module")
def model_and_params():
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    return model, model.init(jax.random.key(7))


@pytest.fixture(scope="module")
def prompt_batch():
    """Right-padded [B, P] prompt ids/mask — the batch path's layout
    (what encode_prompt_batch produces in the trainer)."""
    rs = np.random.RandomState(3)
    prompts = [list(rs.randint(3, 500, (n,))) for n in (6, 4, 9, 5)]
    width = max(len(p) for p in prompts)
    ids = np.zeros((len(prompts), width), np.int32)
    mask = np.zeros_like(ids)
    for i, p in enumerate(prompts):
        ids[i, :len(p)] = p
        mask[i, :len(p)] = 1
    return ids, mask


def _serving_cfg(G=1, **kw):
    base = dict(page_size=4, num_pages=64, num_slots=3,
                max_model_len=32, max_prefill_batch=2)
    if G > 1:
        # G-groups share prompt pages through the prefix cache
        base.update(prefill_chunk=4, prefix_cache=True)
    base.update(kw)
    return ServingConfig(**base)


def _batch_reference(model, params, gen, ids, mask, seeds, G=1):
    fn = jax.jit(build_generate_fn(model, gen, group_size=G,
                                   per_request_seeds=True))
    return fn(params, jnp.asarray(ids), jnp.asarray(mask),
              jnp.asarray(seeds, jnp.uint32))


def _assert_parity(ref, out):
    """Tokens and masks bit-identical; logps to float32 ulp (paged and
    contiguous attention round differently)."""
    rmask = np.asarray(ref["response_mask"])
    assert np.array_equal(rmask, np.asarray(out["response_mask"]))
    assert np.array_equal(
        np.asarray(ref["response_tokens"]) * rmask,
        np.asarray(out["response_tokens"]) * rmask)
    smask = np.asarray(ref["sequence_mask"])
    assert np.array_equal(smask, np.asarray(out["sequence_mask"]))
    assert np.array_equal(np.asarray(ref["sequences"]) * smask,
                          np.asarray(out["sequences"]) * smask)
    np.testing.assert_allclose(
        np.asarray(out["response_logps"]) * rmask,
        np.asarray(ref["response_logps"]) * rmask,
        atol=1e-5, rtol=0)


# ---------------------------------------------------------------------------
# sync-mode bit parity with the seeded batch path
# ---------------------------------------------------------------------------

def test_rollout_parity_greedy(model_and_params, prompt_batch):
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    seeds = derive_rollout_seeds(123, len(ids))
    ref = _batch_reference(model, params, gen, ids, mask, seeds)
    roll = RolloutEngine(model, params, gen, _serving_cfg())
    out = roll.generate(ids, mask, seeds)
    roll.close()
    _assert_parity(ref, out)
    snap = roll.metrics.snapshot()
    assert snap["rollout/rollouts"] == 1
    assert snap["rollout/slot_steps_per_token"] > 0


def test_rollout_parity_sampled(model_and_params, prompt_batch):
    """temperature + top-p + top-k: the serving engine's per-request
    (seed, token-index)-keyed sampler reproduces the batch path's
    stream exactly."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = GenerationConfig(max_new_tokens=6, do_sample=True,
                           temperature=0.9, top_p=0.9, top_k=8,
                           eos_token_id=2, pad_token_id=0)
    seeds = derive_rollout_seeds(123, len(ids))
    ref = _batch_reference(model, params, gen, ids, mask, seeds)
    roll = RolloutEngine(model, params, gen, _serving_cfg())
    out = roll.generate(ids, mask, seeds)
    roll.close()
    _assert_parity(ref, out)


def test_rollout_parity_grouped_prefix_cache(model_and_params,
                                             prompt_batch):
    """G = samples_per_prompt > 1: G seeded copies per prompt, prompt
    pages aliased through the prefix cache — still bit-identical to the
    batch path's in-graph group_size expansion."""
    model, params = model_and_params
    ids, mask = prompt_batch
    G = 2
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=True,
                           temperature=1.1, top_p=0.8, top_k=0,
                           eos_token_id=2, pad_token_id=0)
    seeds = derive_rollout_seeds(123, len(ids) * G)
    ref = _batch_reference(model, params, gen, ids, mask, seeds, G=G)
    roll = RolloutEngine(model, params, gen, _serving_cfg(G=G),
                         samples_per_prompt=G)
    out = roll.generate(ids, mask, seeds)
    roll.close()
    _assert_parity(ref, out)
    assert np.asarray(out["response_tokens"]).shape[0] == len(ids) * G


def test_rollout_seed_count_validated(model_and_params, prompt_batch):
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    with pytest.raises(ValueError):
        RolloutEngine(model, params, gen, _serving_cfg(),
                      samples_per_prompt=0)
    roll = RolloutEngine(model, params, gen, _serving_cfg(),
                         samples_per_prompt=2)
    with pytest.raises(ValueError):        # need B * G seeds
        roll.generate(ids, mask, derive_rollout_seeds(1, len(ids)))
    with pytest.raises(ValueError):        # max_new must cover every row
        roll.generate(ids, mask, derive_rollout_seeds(1, len(ids) * 2),
                      max_new=[MAX_NEW] * len(ids))
    roll.close()


# ---------------------------------------------------------------------------
# in-place weight refit
# ---------------------------------------------------------------------------

def test_refit_zero_recompiles_then_donation(model_and_params,
                                             prompt_batch):
    """The refit contract end to end: same-tree refit changes nothing
    and recompiles nothing; a perturbed tree changes the outputs and
    STILL recompiles nothing; a donated refit frees the old tree's
    device buffers and the engine keeps working."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    seeds = derive_rollout_seeds(7, len(ids))
    roll = RolloutEngine(model, params, gen, _serving_cfg())
    out0 = roll.generate(ids, mask, seeds)
    assert roll.engine.decode_compiles == 1
    pc = roll.engine.prefill_compiles

    # refit the SAME params: identical outputs, zero recompiles
    refitter = WeightRefitter(roll, lambda: params)
    ms = refitter.refit()
    assert ms >= 0
    out1 = roll.generate(ids, mask, seeds)
    assert np.array_equal(np.asarray(out0["response_tokens"]),
                          np.asarray(out1["response_tokens"]))
    assert np.array_equal(np.asarray(out0["response_logps"]),
                          np.asarray(out1["response_logps"]))
    assert roll.engine.decode_compiles == 1
    assert roll.engine.prefill_compiles == pc
    assert roll.metrics.refits.value == 1
    assert roll.metrics.refit_ms.value >= 0

    # perturbed tree (same structure/shapes/dtypes): outputs change,
    # compile counters still pinned
    bumped = jax.tree_util.tree_map(lambda x: x * 1.01, params)
    refitter.refit(bumped)
    out2 = roll.generate(ids, mask, seeds)
    assert not np.array_equal(np.asarray(out0["response_logps"]),
                              np.asarray(out2["response_logps"]))
    assert roll.engine.decode_compiles == 1
    assert roll.engine.prefill_compiles == pc

    # donated refit: the OLD (bumped) tree's buffers are freed eagerly;
    # the engine runs on the fresh tree and reproduces out0
    fresh = jax.tree_util.tree_map(lambda x: x * 1.0, params)
    WeightRefitter(roll, lambda: fresh, donate=True).refit()
    assert any(leaf.is_deleted()
               for leaf in jax.tree_util.tree_leaves(bumped))
    out3 = roll.generate(ids, mask, seeds)
    assert np.array_equal(np.asarray(out0["response_tokens"]),
                          np.asarray(out3["response_tokens"]))
    assert roll.engine.decode_compiles == 1
    roll.close()


def test_publish_params_rejects_mismatched_tree(model_and_params):
    """A refit that would silently retrace must raise instead."""
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    roll = RolloutEngine(model, params, gen, _serving_cfg())
    with pytest.raises(ValueError):        # structure mismatch
        roll.publish_params({"not": "the tree"})
    with pytest.raises(ValueError):        # dtype mismatch
        roll.publish_params(jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float16), params))
    roll.close()


# ---------------------------------------------------------------------------
# pipeline: sync pacing + staleness correction
# ---------------------------------------------------------------------------

def test_pipeline_sync_on_policy(model_and_params, prompt_batch):
    """Sync mode: staleness is always 0 and the truncated-IS corrector
    returns weights ~1 for on-policy rollouts."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=True,
                           temperature=1.0, eos_token_id=2,
                           pad_token_id=0)

    def sample_fn(idx):
        return ids, mask, derive_rollout_seeds(1000 + idx, len(ids))

    pipe = build_rollout_pipeline(model, params, gen, sample_fn,
                                  rows=len(ids),
                                  prompt_width=ids.shape[1],
                                  mode="sync",
                                  serving={"page_size": 4})
    out, staleness = pipe.get(0, params=params)
    assert staleness == 0
    corr = make_staleness_corrector(model, is_clip=2.0)
    w = np.asarray(corr(params, out))
    np.testing.assert_allclose(w, 1.0, atol=1e-3)
    assert np.all(w <= 2.0)

    adv2 = apply_staleness_correction(jnp.ones((len(ids), 3)),
                                      jnp.asarray(w))
    assert adv2.shape == (len(ids), 3)
    adv1 = apply_staleness_correction(jnp.full((len(ids),), 2.0),
                                      jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(adv1), 2.0 * w, atol=1e-6)
    pipe.close()


def _wait_queue_full(pipe, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pipe._q.full():
            return
        time.sleep(0.01)
    raise AssertionError("generator thread never filled the queue")


def test_pipeline_async_staleness_bound(model_and_params, prompt_batch):
    """Async mode bookkeeping: on-policy consumption, bounded-stale
    consumption (stale_rollouts), and discard-regenerate when the
    queued rollout exceeds max_staleness_updates."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=True,
                           temperature=1.0, eos_token_id=2,
                           pad_token_id=0)

    def sample_fn(idx):
        return ids, mask, derive_rollout_seeds(2000 + idx, len(ids))

    pipe = build_rollout_pipeline(model, params, gen, sample_fn,
                                  rows=len(ids),
                                  prompt_width=ids.shape[1],
                                  mode="async",
                                  max_staleness_updates=1,
                                  serving={"page_size": 4})
    try:
        out0, st0 = pipe.get(0, params=params)
        assert st0 == 0
        assert np.asarray(out0["response_tokens"]).shape[0] == len(ids)

        # rollout 1 was generated before these updates: stale by 1,
        # inside the bound -> used with correction
        _wait_queue_full(pipe)
        pipe.notify_updates(1, params=params)
        out1, st1 = pipe.get(1, params=params)
        assert st1 == 1
        assert pipe.metrics.stale_rollouts.value == 1

        # three more updates push the queued rollout past the bound:
        # discarded, refit, regenerated inline -> comes back on-policy
        _wait_queue_full(pipe)
        pipe.notify_updates(3, params=params)
        out2, st2 = pipe.get(2, params=params)
        assert st2 == 0
        assert pipe.metrics.discarded_rollouts.value == 1
        assert np.asarray(out2["response_mask"]).sum() > 0

        with pytest.raises(RuntimeError):   # strict in-order consumption
            pipe.get(7)
    finally:
        pipe.close()


def test_async_handoff_survives_learner_donation(model_and_params,
                                                 prompt_batch):
    """The trainer's jitted update donates its input params
    (``donate_argnums=(0, 1)``), deleting the old buffers in place —
    the very buffers a by-reference async handoff would leave the
    generator thread reading mid-generation ("Array has been
    deleted", reproduced via train_rlhf with ``mode: async``). Pin:
    the pipeline snapshots every tree crossing the thread boundary,
    so deleting the learner's copy after handoff changes nothing."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)

    def sample_fn(idx):
        return ids, mask, derive_rollout_seeds(3000 + idx, len(ids))

    # the learner's live tree: handed over, then "donated" (deleted)
    learner_tree = jax.tree.map(jnp.copy, params)
    pipe = build_rollout_pipeline(model, learner_tree, gen, sample_fn,
                                  rows=len(ids),
                                  prompt_width=ids.shape[1],
                                  mode="async",
                                  max_staleness_updates=1,
                                  serving={"page_size": 4})
    try:
        out0, _ = pipe.get(0, params=learner_tree)
        assert np.asarray(out0["response_tokens"]).shape[0] == len(ids)
        _wait_queue_full(pipe)
        pipe.notify_updates(1, params=learner_tree)
        # the donated update step: the learner's old buffers die NOW,
        # possibly while the generator is still decoding rollout 2
        for leaf in jax.tree_util.tree_leaves(learner_tree):
            leaf.delete()
        out1, st1 = pipe.get(1)          # generated pre-update: stale 1
        assert st1 == 1
        # rollout 2's version snapshot races the notify (0 or 1, both in
        # bound) — the pin is that generation proceeds on owned buffers
        out2, st2 = pipe.get(2)
        assert st2 <= 1
        assert np.asarray(out2["response_mask"]).sum() > 0
    finally:
        pipe.close()


def test_pipeline_rejects_unknown_mode(model_and_params, prompt_batch):
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    with pytest.raises(ValueError):
        build_rollout_pipeline(model, params, gen, lambda i: None,
                               rows=len(ids),
                               prompt_width=ids.shape[1],
                               mode="overlapped")


def test_build_rollout_pipeline_geometry(model_and_params):
    """The derived serving geometry always fits the rollout: a whole
    prompt+response window per slot, pool covers all slots + trash
    page, prefix cache defaulted ON for G > 1."""
    model, params = model_and_params
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    pipe = build_rollout_pipeline(model, params, gen, lambda i: None,
                                  rows=4, prompt_width=9,
                                  samples_per_prompt=2,
                                  serving={"page_size": 4})
    cfg = pipe.rollout.cfg
    assert cfg.page_size == 4
    assert cfg.max_model_len == 16          # ceil4(9 + 5)
    assert cfg.num_slots == 4               # min(rows, 8)
    assert cfg.num_pages == 4 * 4 + 1       # slots * pages/slot + trash
    assert cfg.prefix_cache and cfg.prefill_chunk == 4
    pipe.close()


# ---------------------------------------------------------------------------
# mid-rollout faults + supervisor restart
# ---------------------------------------------------------------------------

def test_mid_rollout_restart_bit_identical(model_and_params,
                                           prompt_batch):
    """rollout_step=0:device_error kills the engine mid-generation; the
    supervisor rebuilds and replays, and the rollout completes with the
    fault-free outputs (tokens exact, logps to float32 ulp)."""
    model, params = model_and_params
    ids, mask = prompt_batch
    gen = GenerationConfig(max_new_tokens=MAX_NEW, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    seeds = derive_rollout_seeds(42, len(ids))

    base_roll = RolloutEngine(model, params, gen, _serving_cfg())
    base = base_roll.generate(ids, mask, seeds)
    base_roll.close()

    roll = RolloutEngine(
        model, params, gen,
        _serving_cfg(fault_plan="rollout_step=0:device_error"),
        supervisor=True)
    out = roll.generate(ids, mask, seeds)
    assert roll.supervisor.restarts >= 1
    roll.close()

    rmask = np.asarray(base["response_mask"])
    assert np.array_equal(rmask, np.asarray(out["response_mask"]))
    assert np.array_equal(
        np.asarray(base["response_tokens"]) * rmask,
        np.asarray(out["response_tokens"]) * rmask)
    np.testing.assert_allclose(
        np.asarray(out["response_logps"]) * rmask,
        np.asarray(base["response_logps"]) * rmask,
        atol=1e-5, rtol=0)


# ---------------------------------------------------------------------------
# metrics + bench
# ---------------------------------------------------------------------------

def test_rollout_metrics_snapshot_names():
    """The rollout/* panel matches the CATALOG (check_metric_names
    gates the docs table; this pins the runtime side)."""
    snap = RolloutMetrics().snapshot()
    assert set(snap) == {
        "rollout/rollouts", "rollout/gen_tokens_per_s",
        "rollout/slot_steps_per_token",
        "rollout/padding_waste_recovered",
        "rollout/refits", "rollout/refit_ms",
        "rollout/staleness_updates", "rollout/stale_rollouts",
        "rollout/discarded_rollouts",
    }


def test_bench_rollout_recovers_padding_waste():
    """The A/B the subsystem exists for: on a long-tail response-length
    mix, continuous batching spends measurably fewer slot-steps per
    generated token than the fixed-shape batch path."""
    import bench
    row = bench.run_rollout_bench()
    assert row["metric"] == "rollout_padding_waste_recovered"
    d = row["detail"]
    assert 0.0 < row["value"] < 1.0
    assert (d["serving_slot_steps_per_token"]
            < d["batch_slot_steps_per_token"])
