"""Anomaly auto-triage (docs/OBSERVABILITY.md "Anomaly auto-capture"):
rolling median/MAD detection, the one-shot capture state machine, and
the full acceptance loop — a DLA_FAULT_PLAN checkpoint stall trips the
detector exactly once, the capture leaves a loadable Chrome trace plus
a ``postmortem_anomaly.json`` referencing it, and ``dla-doctor``
correlates the anomaly back to the checkpoint stall in its ranked
diagnosis.
"""
import json

import jax
import numpy as np
import pytest

from dla_tpu.telemetry import (
    AnomalyConfig,
    AnomalyMonitor,
    FlightRecorder,
    MetricRegistry,
    RollingDetector,
)
from dla_tpu.telemetry.trace import Tracer, install_tracer


# ---------------------------------------------------------------------------
# detector: robust z over a rolling window
# ---------------------------------------------------------------------------

def test_rolling_detector_warmup_then_breach():
    det = RollingDetector(window=16, warmup=8, z_threshold=6.0)
    assert det.observe(1000.0) is None     # warmup: even a spike passes
    for _ in range(9):
        assert det.observe(10.0) is None
    breach = det.observe(500.0)
    assert breach is not None
    assert breach["z"] >= 6.0
    assert breach["median"] == pytest.approx(10.0, rel=0.5)


def test_rolling_detector_excludes_breaches_from_window():
    """A sustained excursion must not teach the detector that slow is
    normal: breaching samples never enter the window."""
    det = RollingDetector(window=16, warmup=0, z_threshold=6.0)
    for _ in range(10):
        det.observe(10.0)
    for _ in range(20):
        assert det.observe(500.0) is not None   # every one still breaches


def test_rolling_detector_one_sided():
    det = RollingDetector(window=16, warmup=0, z_threshold=6.0)
    for _ in range(10):
        det.observe(10.0)
    assert det.observe(0.001) is None      # fast is never anomalous


def test_anomaly_config_absent_or_disabled_is_none():
    assert AnomalyConfig.from_config(None) is None
    assert AnomalyConfig.from_config({"enabled": False}) is None
    cfg = AnomalyConfig.from_config({"window": 8, "unknown_key": 1})
    assert cfg is not None and cfg.window == 8


# ---------------------------------------------------------------------------
# monitor: one-shot capture, rate limiting, recompile triggers
# ---------------------------------------------------------------------------

def _monitor(tmp_path, **over):
    cfg = AnomalyConfig(**{**dict(window=16, warmup_steps=8,
                                  z_threshold=6.0, capture_steps=2,
                                  cooldown_steps=100, max_captures=1),
                           **over})
    reg = MetricRegistry()
    rec = FlightRecorder(capacity=64, out_dir=str(tmp_path))
    tracer = Tracer(enabled=True, capacity=256,
                    path=str(tmp_path / "trace.json"))
    mon = AnomalyMonitor(cfg, recorder=rec, tracer=tracer,
                         registry=reg, out_dir=str(tmp_path))
    return mon, reg, rec


def _drive(mon, steps, value=10.0, spike_at=None, spike=500.0):
    for step in range(1, steps + 1):
        x = spike if step == spike_at else value
        mon.observe("step_ms", x, step)
        mon.on_step(step)


def test_breach_arms_exactly_one_capture_with_evidence(tmp_path):
    mon, reg, rec = _monitor(tmp_path)
    _drive(mon, steps=16, spike_at=12)
    assert mon.triggers == 1 and mon.captures == 1
    snap = reg.snapshot()
    assert snap["telemetry/anomaly/triggers"] == 1.0
    assert snap["telemetry/anomaly/captures"] == 1.0

    # the postmortem names the metric, window stats, and the trace path
    pm_path = tmp_path / "postmortem_anomaly.json"
    assert pm_path.exists()
    doc = json.loads(pm_path.read_text())
    block = doc["anomaly"]
    assert block["trigger"] == "metric" and block["metric"] == "step_ms"
    assert block["trigger_step"] == 12
    assert block["z"] >= 6.0
    # K=2 aftermath counted from the trigger step itself
    assert block["capture_end_step"] == 13

    # the referenced capture trace exists and is loadable Chrome JSON
    trace = tmp_path / "anomaly_trace_step12.json"
    assert block["trace_path"] == str(trace)
    parsed = json.loads(trace.read_text())
    assert isinstance(parsed.get("traceEvents"), list)


def test_capture_budget_and_cooldown_rate_limit(tmp_path):
    mon, _, _ = _monitor(tmp_path, max_captures=1, cooldown_steps=100)
    _drive(mon, steps=40, spike_at=12)
    # a second excursion after the first finished: budget says no
    mon.observe("step_ms", 500.0, 41)
    mon.on_step(41)
    assert mon.triggers == 1 and mon.captures == 1
    assert len(mon.postmortem_paths) == 1

    # with budget left, cooldown still spaces triggers out
    mon2, _, _ = _monitor(tmp_path / "b", max_captures=4,
                          cooldown_steps=50)
    (tmp_path / "b").mkdir()
    _drive(mon2, steps=16, spike_at=12)
    mon2.observe("step_ms", 500.0, 20)      # 8 steps later: cooling down
    assert mon2.triggers == 1
    mon2.observe("step_ms", 500.0, 80)      # past cooldown: fires again
    assert mon2.triggers == 2


def test_unattributed_recompile_triggers_after_warmup(tmp_path):
    mon, _, rec = _monitor(tmp_path)
    mon.note_recompile(2, "train_step", attributed=False)   # warmup
    mon.note_recompile(20, "train_step", attributed=True)   # explained
    mon.note_recompile(21, "train_step", attributed=True, first=True)
    assert mon.triggers == 0
    mon.note_recompile(22, "train_step", attributed=False)  # the anomaly
    assert mon.triggers == 1
    anomalies = [e for e in rec.events if e["kind"] == "anomaly"]
    assert anomalies[0]["trigger"] == "recompile"
    assert anomalies[0]["fn"] == "train_step"


def test_close_flushes_capture_cut_short(tmp_path):
    mon, _, _ = _monitor(tmp_path, capture_steps=50)
    _drive(mon, steps=12, spike_at=12)
    assert mon.captures == 0               # capture still open
    mon.close()
    assert mon.captures == 1
    assert (tmp_path / "postmortem_anomaly.json").exists()


# ---------------------------------------------------------------------------
# THE acceptance loop: fault-injected checkpoint stall -> one capture
# -> dla-doctor correlates it
# ---------------------------------------------------------------------------

def test_checkpoint_stall_autocapture_and_doctor_correlation(
        mesh8, tmp_path, monkeypatch):
    """DLA_FAULT_PLAN injects an io_error into the async checkpoint at
    step 5; the retry backoff stalls the step-10 save, the step-time
    detector trips EXACTLY once, the capture leaves a loadable trace +
    postmortem_anomaly.json referencing it, and dla-doctor ranks the
    anomaly->checkpoint correlation first."""
    from dla_tpu.resilience import ENV_VAR
    from tests.test_telemetry import BatchIter, _make_trainer
    out = tmp_path / "run"
    monkeypatch.setenv(ENV_VAR, "step=5:io_error")
    try:
        with jax.sharding.set_mesh(mesh8):
            tr = _make_trainer(
                mesh8, out, max_steps=14, save_every=5,
                telemetry={"trace": {"enabled": True},
                           "anomaly": {"window": 16, "warmup_steps": 8,
                                       "z_threshold": 6.0,
                                       "capture_steps": 2,
                                       "cooldown_steps": 50,
                                       "max_captures": 1}},
                resilience={"async_checkpointing": True,
                            "save_retries": 3, "retry_backoff_s": 0.8})
            it = BatchIter()
            tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
            tr.checkpointer.wait()
    finally:
        install_tracer(None)

    assert tr.checkpointer.retries_total == 1
    assert tr.anomaly is not None
    assert tr.anomaly.triggers == 1        # exactly one auto-capture
    assert tr.anomaly.captures == 1
    snap = tr.registry.snapshot()
    assert snap["telemetry/anomaly/captures"] == 1.0

    pm = out / "postmortem_anomaly.json"
    assert pm.exists()
    block = json.loads(pm.read_text())["anomaly"]
    assert block["metric"] == "step_ms"
    assert block["trigger_step"] == 10     # the stalled save's step
    trace = out / f"anomaly_trace_step{block['trigger_step']}.json"
    assert block["trace_path"] == str(trace)
    parsed = json.loads(trace.read_text())  # loadable Chrome trace
    assert len(parsed["traceEvents"]) > 0

    # the offline correlator closes the loop: anomaly -> checkpoint
    from tools.dla_doctor import diagnose, load_run
    run = load_run(out)
    findings = diagnose(run, out)
    assert findings, "doctor produced no findings"
    top = findings[0]
    assert top["rule"] == "anomaly-correlated"
    assert "checkpoint" in top["message"]
    assert "loadable" in top["message"]
    cause = top["data"]["cause"]
    assert cause["kind"].startswith("ckpt_")
