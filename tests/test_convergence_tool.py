"""tools/convergence_run.py — the >=1B DPO convergence runner (VERDICT
r3 item 6) must demonstrably converge at its CPU-validation scale, so
the on-chip run is a scale-up, not a debug session."""
import importlib.util
import os

import pytest


def _load_tool():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "convergence_run.py")
    spec = importlib.util.spec_from_file_location("convergence_run", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_convergence_run_tiny(tmp_path):
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mod = _load_tool()
    summary = mod.main(steps=120, out_dir=str(tmp_path))
    # DPO from ln(2): the loss must fall and fresh-sample preference
    # must be essentially solved at this toy scale
    assert summary["loss_last10_mean"] < 0.67
    assert summary["preference_rate_last10_mean"] > 0.9
    assert (tmp_path / "metrics.jsonl").is_file()
    assert (tmp_path / "summary.md").is_file()
