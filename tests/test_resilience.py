"""Fault-tolerance tests (docs/RESILIENCE.md): the deterministic fault
plan, the NaN guard's retry/rollback, async checkpointing with injected
I/O errors, preemption with emergency save + resume, corrupt-checkpoint
fallback, the watchdog, and the serving engine's deadline/drain paths.

THE acceptance pin: a run through an injected io_error + nan + preempt,
resumed after the preemption, reaches the same final step with
bit-identical parameters to a fault-free run — and the guard adds zero
extra train-step compiles (trace-time counter pinned at 1).
"""
import os
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dla_tpu.checkpoint import Checkpointer
from dla_tpu.resilience import (
    ENV_VAR,
    RETRY,
    ROLLBACK,
    SKIP,
    AsyncCheckpointer,
    FaultPlan,
    GuardConfig,
    GuardState,
    PreemptionExit,
    PreemptionHandler,
    ResilienceConfig,
    Watchdog,
)


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------

def test_fault_plan_parse_and_one_shot_take():
    plan = FaultPlan.parse("step=12:io_error; step=5:nan ;step=50:preempt")
    # entries sort by step; spec() round-trips
    assert plan.spec() == "step=5:nan;step=12:io_error;step=50:preempt"
    assert bool(plan)
    # not due yet
    assert plan.take("nan", 4) is None
    # fires at the first poll with step >= entry.step, exactly once
    hit = plan.take("nan", 7)
    assert hit is not None and hit.step == 5
    assert plan.take("nan", 7) is None
    # other kinds unaffected, and each is one-shot too
    assert plan.take("io_error", 100).kind == "io_error"
    assert plan.take("io_error", 100) is None
    assert [f.kind for f in plan.pending()] == ["preempt"]


def test_fault_plan_arg_and_empty():
    plan = FaultPlan.parse("step=3:hang:0.25")
    assert plan.take("hang", 3).arg == 0.25
    empty = FaultPlan.parse("")
    assert not empty and empty.take("nan", 10 ** 9) is None


def test_fault_plan_rejects_bad_specs():
    with pytest.raises(ValueError):
        FaultPlan.parse("step=1")            # missing kind
    with pytest.raises(ValueError):
        FaultPlan.parse("step=1:bogus")      # unknown kind
    with pytest.raises(ValueError):
        FaultPlan.parse("at=1:nan")          # wrong key


def test_resilience_config_env_and_block(monkeypatch):
    monkeypatch.setenv(ENV_VAR, "step=7:nan")
    rc = ResilienceConfig.from_config(None)
    # conservative code defaults: only the guard is on by default
    assert not rc.async_checkpointing and not rc.preemption
    assert not rc.watchdog_enabled
    assert rc.guard.enabled
    assert rc.fault_plan.spec() == "step=7:nan"      # env picked up
    # an explicit config block overrides the env plan
    rc2 = ResilienceConfig.from_config(
        {"fault_plan": "step=1:hang:0.5", "async_checkpointing": True,
         "guard": {"max_consecutive_bad": 5, "rollback": False}})
    assert rc2.async_checkpointing
    assert rc2.fault_plan.entries[0].arg == 0.5
    assert rc2.guard.max_consecutive_bad == 5 and not rc2.guard.rollback


# ---------------------------------------------------------------------------
# guard policy (host half)
# ---------------------------------------------------------------------------

def test_guard_retry_then_rollback_sequence():
    g = GuardState(GuardConfig(max_consecutive_bad=3))
    assert g.on_step(True, 2.0) is None
    assert g.ema == 2.0                       # cold EMA seeds on first good
    assert g.on_step(False, float("nan")) == RETRY
    assert g.on_step(False, float("nan")) == RETRY
    assert g.on_step(False, float("nan")) == ROLLBACK
    assert g.consecutive_bad == 0             # counter reset after verdict
    assert g.bad_steps_total == 3 and g.rollbacks == 1
    # a good step in between resets the consecutive counter
    assert g.on_step(False, float("nan")) == RETRY
    assert g.on_step(True, 1.0) is None
    assert g.on_step(False, float("nan")) == RETRY
    g.reset_ema()
    assert g.ema == 0.0


def test_guard_skip_when_rollback_disabled():
    g = GuardState(GuardConfig(max_consecutive_bad=1, rollback=False))
    assert g.on_step(False, float("inf")) == SKIP
    assert g.rollbacks == 0


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_beat_defers_then_fires():
    fired = threading.Event()
    dumps = []

    def on_hang(dump):
        dumps.append(dump)
        fired.set()

    wd = Watchdog(timeout_s=0.2, poll_s=0.03, on_hang=on_hang, abort=False)
    wd.start()
    try:
        for _ in range(10):                   # heartbeats keep it quiet
            wd.beat()
            time.sleep(0.04)
        assert not wd.fired
        assert fired.wait(timeout=5.0)        # stop beating -> it trips
        assert wd.fired
        # the dump attributes the hang: every thread's stack, named
        assert "stack dump" in dumps[0]
        assert "MainThread" in dumps[0]
    finally:
        wd.stop()


def test_watchdog_rejects_nonpositive_timeout():
    with pytest.raises(ValueError):
        Watchdog(timeout_s=0.0)


# ---------------------------------------------------------------------------
# preemption handler
# ---------------------------------------------------------------------------

def test_preemption_sigterm_sets_flag_and_agreement():
    h = PreemptionHandler(signals=(signal.SIGTERM,))
    h.install()
    try:
        assert not h.requested_local()
        assert not h.should_checkpoint(0)
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)                      # let the handler run
        assert h.requested_local()
        assert h.should_checkpoint(1)         # single host: plain flag read
        assert h.should_checkpoint(2)         # sticky
    finally:
        h.uninstall()


def test_preemption_exit_is_clean_systemexit():
    exc = PreemptionExit(17)
    assert isinstance(exc, SystemExit)
    assert exc.code == 0 and exc.step == 17


# ---------------------------------------------------------------------------
# async checkpointer
# ---------------------------------------------------------------------------

def _ck_tree():
    return {"w": jnp.arange(16, dtype=jnp.float32).reshape(4, 4),
            "n": jnp.zeros((), jnp.int32)}


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_async_checkpointer_roundtrip(tmp_path):
    ck = AsyncCheckpointer(str(tmp_path / "ck"))
    tree = _ck_tree()
    ck.save(1, tree, aux={"step": 1})
    ck.wait()
    assert not ck.in_flight
    assert ck.saves_started == ck.saves_completed == 1
    assert ck.latest_tag() == "step_00000001"
    got, aux = ck.restore(tree)
    assert aux["step"] == 1
    _assert_tree_equal(tree, got)


def test_async_checkpointer_retries_injected_io_error(tmp_path):
    ck = AsyncCheckpointer(str(tmp_path / "ck"), max_retries=3,
                           backoff_s=0.01,
                           faults=FaultPlan.parse("step=0:io_error"))
    tree = _ck_tree()
    ck.save(2, tree, aux={"step": 2})
    ck.wait()                                 # retry recovered in background
    assert ck.retries_total == 1
    assert ck.saves_completed == 1
    got, _ = ck.restore(tree, tag="step_00000002")
    _assert_tree_equal(tree, got)


def test_async_checkpointer_surfaces_exhausted_retries(tmp_path):
    # two armed io_errors vs max_retries=1: both attempts fail and the
    # terminal error must re-raise on the TRAINING thread, not vanish
    ck = AsyncCheckpointer(
        str(tmp_path / "ck"), max_retries=1, backoff_s=0.001,
        faults=FaultPlan.parse("step=0:io_error;step=0:io_error"))
    tree = _ck_tree()
    ck.save(1, tree)
    with pytest.raises(OSError, match="injected io_error"):
        ck.wait()
    assert ck.retries_total == 1 and ck.saves_completed == 0
    # the checkpointer stays usable once the error has been surfaced
    ck.save(2, tree, aux={"step": 2})
    ck.wait()
    assert ck.latest_tag() == "step_00000002"


def test_async_checkpointer_exposes_last_error_age(tmp_path):
    """The flaky-FS gauges: ``last_error_age_s()`` is -1 until a write
    attempt fails, then tracks the age of the newest OSError — even when
    the retry recovered (a flaky FS shows up as a small, churning age
    next to a growing ``retries_total``)."""
    ck = AsyncCheckpointer(str(tmp_path / "ck"), max_retries=3,
                           backoff_s=0.01,
                           faults=FaultPlan.parse("step=0:io_error"))
    assert ck.last_error_age_s() == -1.0
    assert ck.last_error is None
    ck.save(1, _ck_tree(), aux={"step": 1})
    ck.wait()                                 # retry recovered
    assert ck.retries_total == 1 and ck.saves_completed == 1
    age = ck.last_error_age_s()
    assert 0.0 <= age < 60.0
    assert "injected io_error" in ck.last_error
    time.sleep(0.02)
    assert ck.last_error_age_s() > age        # it is an age, not a flag


def test_sweep_stale_tmp_and_atomic_latest(tmp_path):
    ck = Checkpointer(str(tmp_path / "ck"))
    ck.save(1, _ck_tree(), aux={"step": 1})
    # plant the debris a mid-write crash leaves behind
    (ck.dir / ".tmp_step_00000099").mkdir()
    (ck.dir / ".tmp_step_00000099" / "w.npy").write_bytes(b"junk")
    (ck.dir / ".latest.tmp").write_text("step_000000")  # truncated pointer
    removed = ck.sweep_stale_tmp()
    assert sorted(removed) == [".latest.tmp", ".tmp_step_00000099"]
    assert not (ck.dir / ".tmp_step_00000099").exists()
    # the real pointer was written atomically and survives the sweep
    assert (ck.dir / "latest").read_text().strip() == "step_00000001"
    assert ck.latest_tag() == "step_00000001"
    assert ck.sweep_stale_tmp() == []         # idempotent


# ---------------------------------------------------------------------------
# trainer integration: a tiny deterministic regression problem on mesh8
# ---------------------------------------------------------------------------

DIM = 8


def _make_batch(i, bs=8):
    rs = np.random.RandomState(1000 + i)
    x = rs.normal(size=(bs, DIM)).astype(np.float32)
    w_true = np.arange(1, DIM + 1, dtype=np.float32)
    return {"x": x, "y": (x @ w_true).astype(np.float32)}


class CountingIter:
    """Deterministic stream whose position is exact resume state
    (data.prefetch=0 keeps the trainer from wrapping it)."""

    def __init__(self):
        self.i = 0

    def __iter__(self):
        return self

    def __next__(self):
        b = _make_batch(self.i)
        self.i += 1
        return b

    def state_dict(self):
        return {"i": self.i}

    def load_state_dict(self, state):
        self.i = int(state["i"])


def _linear_loss(params, frozen, batch, rng):
    del frozen, rng
    pred = batch["x"] @ params["w"]
    return jnp.mean((pred - batch["y"]) ** 2), {}


def _make_trainer(mesh, out_dir, *, max_steps=12, save_every=4,
                  resilience=None):
    from dla_tpu.training.trainer import Trainer
    config = {
        "experiment_name": "resilience_test",
        "data": {"prefetch": 0},
        "optimization": {"total_batch_size": 8, "micro_batch_size": 1,
                         "learning_rate": 1e-2, "max_train_steps": max_steps,
                         "lr_scheduler": "constant", "max_grad_norm": 1.0},
        "logging": {"output_dir": str(out_dir), "log_dir": None,
                    "save_every_steps": save_every,
                    "log_every_steps": 10 ** 6},
        "hardware": {"gradient_accumulation_steps": 2},
    }
    if resilience is not None:
        config["resilience"] = resilience
    return Trainer(config=config, mesh=mesh, loss_fn=_linear_loss,
                   params={"w": jnp.zeros((DIM,), jnp.float32)},
                   param_specs={"w": P()})


def test_faulted_preempted_run_bit_identical_to_fault_free(mesh8, tmp_path):
    """THE acceptance pin: io_error (checkpoint write retried) + nan
    (guard retries the same batch with the same rng) + preempt (emergency
    save, clean exit, resume) must reproduce the fault-free run's final
    parameters bit-for-bit — and the guard/injector must add zero extra
    train-step compiles."""
    with jax.sharding.set_mesh(mesh8):
        ref = _make_trainer(mesh8, tmp_path / "ref",
                            resilience={"async_checkpointing": True})
        it_ref = CountingIter()
        p_ref = ref.fit(it_ref, rng=jax.random.key(42),
                        data_state=it_ref.state_dict)
        ref_bytes = np.asarray(p_ref["w"]).tobytes()
        assert ref.step == 12
        assert ref.train_step_compiles == 1

        faults = "step=3:io_error;step=5:nan;step=8:preempt"
        tr = _make_trainer(
            mesh8, tmp_path / "faulted",
            resilience={"async_checkpointing": True, "save_retries": 3,
                        "retry_backoff_s": 0.01, "preemption": True,
                        "fault_plan": faults})
        it = CountingIter()
        with pytest.raises(PreemptionExit) as exc_info:
            tr.fit(it, rng=jax.random.key(42), data_state=it.state_dict)
        assert exc_info.value.code == 0       # clean, resumable exit
        assert exc_info.value.step == 8       # emergency save boundary
        assert tr.guard.bad_steps_total == 1  # the injected NaN, retried
        assert tr.checkpointer.retries_total == 1     # the injected io_error
        assert tr.train_step_compiles == 1    # guard+injector: zero recompiles

        resumed = _make_trainer(mesh8, tmp_path / "faulted",
                                resilience={"async_checkpointing": True})
        it2 = CountingIter()
        p_res = resumed.fit(it2, rng=jax.random.key(42),
                            data_state=it2.state_dict, resume=True)
        assert it2.i == 12                    # data position resumed at 8
        assert resumed.step == 12
        assert resumed.train_step_compiles == 1
        assert np.asarray(p_res["w"]).tobytes() == ref_bytes


def test_persistent_nan_rolls_back_and_training_continues(mesh8, tmp_path):
    """A batch that NaNs deterministically exhausts the guard's retries;
    the trainer restores the last checkpoint, drops the poison batch,
    and still reaches max_steps with finite params."""
    with jax.sharding.set_mesh(mesh8):
        tr = _make_trainer(
            mesh8, tmp_path / "run", max_steps=8, save_every=4,
            resilience={"async_checkpointing": True,
                        "fault_plan": "step=5:nan;step=5:nan;step=5:nan",
                        "guard": {"max_consecutive_bad": 3}})
        it = CountingIter()
        p = tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        assert tr.step == 8
        assert tr.guard.bad_steps_total == 3
        assert tr.guard.rollbacks == 1        # rolled back to step_00000004
        assert tr.train_step_compiles == 1
        assert np.isfinite(np.asarray(p["w"])).all()


def test_resume_falls_back_past_corrupt_checkpoints(mesh8, tmp_path):
    """Satellite (d): a truncated index.json (ValueError) and a missing
    shard file (OSError) must each fall back to the previous good tag
    instead of crashing the resume."""
    with jax.sharding.set_mesh(mesh8):
        out = tmp_path / "run"
        tr = _make_trainer(mesh8, out, max_steps=8, save_every=4)
        it = CountingIter()
        tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        ckdir = tr.checkpointer.dir
        assert (ckdir / "latest").read_text().strip() == "final"

        # corrupt `final`: a write that died mid-index
        (ckdir / "final" / "index.json").write_text('{"leaves": [')
        t2 = _make_trainer(mesh8, out, max_steps=8, save_every=4)
        aux = t2.try_resume()
        assert t2.step == 8                   # fell back to step_00000008
        assert aux["step"] == 8

        # additionally lose a shard file from step_00000008
        victim = sorted((ckdir / "step_00000008").glob("*.npy"))[0]
        victim.unlink()
        t3 = _make_trainer(mesh8, out, max_steps=8, save_every=4)
        t3.try_resume()
        assert t3.step == 4                   # next fallback: step_00000004


def test_resume_with_every_tag_corrupt_raises_instead_of_looping(
        mesh8, tmp_path):
    """When `final` AND every step_* tag is corrupt there is nothing to
    fall back to: try_resume must surface the original corruption error
    promptly — not spin through fallbacks forever, and not leave the
    trainer half-restored."""
    with jax.sharding.set_mesh(mesh8):
        out = tmp_path / "run"
        tr = _make_trainer(mesh8, out, max_steps=8, save_every=4)
        it = CountingIter()
        tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        ckdir = tr.checkpointer.dir
        ntags = 0
        for tag_dir in ckdir.iterdir():
            if tag_dir.is_dir():
                (tag_dir / "index.json").write_text('{"leaves": [')
                ntags += 1
        assert ntags >= 3                     # final + two step tags

        t2 = _make_trainer(mesh8, out, max_steps=8, save_every=4)
        with pytest.raises(ValueError):       # the ORIGINAL error, loud
            t2.try_resume()
        assert t2.step == 0                   # no half-restored state


# ---------------------------------------------------------------------------
# serving: per-request deadlines + graceful drain
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def serve_setup():
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer
    cfg = get_model_config("tiny")
    model = Transformer(cfg)
    params = model.init(jax.random.key(7))
    gen = GenerationConfig(max_new_tokens=5, do_sample=False,
                           eos_token_id=2, pad_token_id=0)
    return model, params, gen


def _engine(serve_setup, clock=None, **cfg_kw):
    from dla_tpu.serving import ServingConfig, ServingEngine
    model, params, gen = serve_setup
    kw = dict(page_size=4, num_pages=32, num_slots=2, max_model_len=32,
              max_prefill_batch=2)
    kw.update(cfg_kw)
    extra = {"now": clock} if clock is not None else {}
    return ServingEngine(model, params, gen, ServingConfig(**kw), **extra)


def _prompts(n, seed=5):
    rs = np.random.RandomState(seed)
    return [list(rs.randint(3, 500, (4,))) for _ in range(n)]


def test_serving_deadline_times_out_queued_and_running(serve_setup):
    from dla_tpu.serving import RequestState
    t = {"now": 0.0}
    eng = _engine(serve_setup, clock=lambda: t["now"], num_slots=1)
    p = _prompts(3)
    r_run = eng.submit(p[0], 5, deadline_s=1.0)     # admitted first
    r_queued = eng.submit(p[1], 5, deadline_s=0.5)  # one slot: waits
    r_free = eng.submit(p[2], 5)                    # no deadline
    eng.step()                                      # r_run prefills+decodes
    assert eng.result(r_run).generated              # sunk tokens exist
    t["now"] = 2.0
    eng.step()                                      # both deadlines passed
    assert eng.result(r_run).state is RequestState.TIMEOUT
    assert eng.result(r_run).finish_reason == "timeout"
    assert eng.result(r_run).generated              # kept on timeout
    assert eng.result(r_queued).state is RequestState.TIMEOUT
    assert not eng.result(r_queued).generated       # never started
    results = eng.run_until_drained(max_steps=500)
    assert results[r_free].state is RequestState.FINISHED
    assert eng.metrics.requests_timed_out.value == 2
    assert eng.cache.allocator.used_count == 0      # slot+pages reclaimed
    eng.scheduler.assert_consistent()


def test_serving_drain_closes_admission_and_sheds_unstarted(serve_setup):
    from dla_tpu.serving import RequestState
    eng = _engine(serve_setup, num_slots=1)
    p = _prompts(3, seed=9)
    r_run = eng.submit(p[0], 5)
    r_waiting = eng.submit(p[1], 5)
    eng.step()                                      # r_run takes the slot
    eng.begin_drain()
    eng.begin_drain()                               # idempotent
    assert eng.draining
    # never-started queued request was shed; admission is closed
    assert eng.result(r_waiting).finish_reason == "cancelled"
    assert eng.metrics.requests_cancelled.value == 1
    with pytest.raises(RuntimeError, match="draining"):
        eng.submit(p[2], 5)
    # the in-flight decode runs to completion — nothing dropped mid-token
    results = eng.run_until_drained(max_steps=500)
    assert results[r_run].state is RequestState.FINISHED
    assert len(results[r_run].generated) > 0
    assert eng.cache.allocator.used_count == 0
    eng.scheduler.assert_consistent()


def test_serving_sigterm_triggers_drain(serve_setup):
    eng = _engine(serve_setup)
    eng.install_drain_handler()
    assert eng._old_handlers is not None
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)                            # deliver the signal
        assert eng.draining
    finally:
        for sig, old in eng._old_handlers.items():
            signal.signal(sig, old)


def test_serving_preemption_notice_mid_drain_is_idempotent(serve_setup):
    """Preemption notices landing MID-DRAIN — the cluster agent retries
    SIGTERM, plus a programmatic PreemptionHandler.request() — must not
    double-cancel the already-shed queue or disturb the in-flight
    decode: the drain keeps its nothing-dropped-mid-token guarantee."""
    from dla_tpu.resilience.preemption import PreemptionHandler
    from dla_tpu.serving import RequestState
    eng = _engine(serve_setup, num_slots=1)
    eng.install_drain_handler()
    handler = PreemptionHandler(recorder=eng.recorder)
    try:
        p = _prompts(2, seed=13)
        r_run = eng.submit(p[0], 5)
        r_wait = eng.submit(p[1], 5)
        eng.step()                          # r_run holds the slot
        eng.begin_drain()                   # drain begins: queue shed
        assert eng.result(r_wait).finish_reason == "cancelled"
        cancelled = eng.metrics.requests_cancelled.value
        eng.step()                          # mid-drain...
        os.kill(os.getpid(), signal.SIGTERM)    # ...the retry arrives
        time.sleep(0.05)
        handler.request()                   # and the agent RPC path
        assert eng.draining
        assert handler.requested_local()
        # idempotent: no double cancellation, no new terminal states
        assert eng.metrics.requests_cancelled.value == cancelled
        results = eng.run_until_drained(max_steps=500)
        assert results[r_run].state is RequestState.FINISHED
        assert len(results[r_run].generated) > 0
        # the RPC-path request landed on the engine's flight recorder
        assert any(e["kind"] == "preempt_requested"
                   for e in eng.recorder.events)
        assert eng.cache.allocator.used_count == 0
        eng.scheduler.assert_consistent()
    finally:
        for sig, old in eng._old_handlers.items():
            signal.signal(sig, old)
        eng.close()
