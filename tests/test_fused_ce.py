"""Fused unembed+logprob (ops.fused_ce) parity with the materializing
path (ops.losses): values and gradients, CE and sequence-logp, chunk
boundaries, bias, and IGNORE_INDEX masking."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dla_tpu.ops.fused_ce import (
    fused_cross_entropy_loss,
    fused_sequence_logprob_mean,
    fused_token_logprobs,
)
from dla_tpu.ops.losses import (
    cross_entropy_loss,
    sequence_logprob_mean,
    token_logprobs,
)


def _setup(b=2, t=12, d=16, v=97, seed=0):
    rs = np.random.RandomState(seed)
    hidden = jnp.asarray(rs.randn(b, t, d).astype(np.float32))
    w = jnp.asarray(rs.randn(d, v).astype(np.float32) * 0.1)
    targets = jnp.asarray(rs.randint(0, v, (b, t)), jnp.int32)
    return hidden, w, targets


@pytest.mark.parametrize("chunk", [4, 7, 1024])
def test_token_logprobs_parity(chunk):
    """Chunk sizes that divide, don't divide, and exceed B*T."""
    hidden, w, targets = _setup()
    got = fused_token_logprobs(hidden, w, targets, chunk=chunk)
    want = token_logprobs(hidden @ w, targets)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_token_logprobs_bias():
    hidden, w, targets = _setup(seed=1)
    bias = jnp.asarray(np.random.RandomState(2).randn(w.shape[1]), jnp.float32)
    got = fused_token_logprobs(hidden, w, targets, bias=bias, chunk=8)
    want = token_logprobs(hidden @ w + bias, targets)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_cross_entropy_parity_and_grads():
    hidden, w, labels = _setup(seed=3)
    labels = labels.at[0, :4].set(-100)  # prompt masking
    labels = labels.at[1, 9:].set(-100)

    def loss_fused(h, w):
        return fused_cross_entropy_loss(h, w, labels, chunk=8)[0]

    def loss_ref(h, w):
        return cross_entropy_loss(h @ w, labels)[0]

    lf = loss_fused(hidden, w)
    lr = loss_ref(hidden, w)
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-6)

    gf = jax.grad(loss_fused, argnums=(0, 1))(hidden, w)
    gr = jax.grad(loss_ref, argnums=(0, 1))(hidden, w)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_sequence_logprob_parity_and_grads():
    hidden, w, ids = _setup(b=3, t=10, seed=4)
    mask = jnp.asarray(
        np.stack([[1] * 10, [1] * 7 + [0] * 3, [1] * 5 + [0] * 5]),
        jnp.int32)

    def f_fused(h):
        return jnp.sum(fused_sequence_logprob_mean(h, w, ids, mask, chunk=8))

    def f_ref(h):
        return jnp.sum(sequence_logprob_mean(h @ w, ids, mask))

    np.testing.assert_allclose(float(f_fused(hidden)), float(f_ref(hidden)),
                               rtol=1e-6)
    gf = jax.grad(f_fused)(hidden)
    gr = jax.grad(f_ref)(hidden)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=1e-4, atol=1e-5)


def test_bias_grads():
    hidden, w, labels = _setup(seed=5)
    bias = jnp.asarray(np.random.RandomState(6).randn(w.shape[1]) * 0.1,
                       jnp.float32)

    def loss_fused(bb):
        return fused_cross_entropy_loss(hidden, w, labels, bias=bb, chunk=8)[0]

    def loss_ref(bb):
        return cross_entropy_loss(hidden @ w + bb, labels)[0]

    np.testing.assert_allclose(float(loss_fused(bias)), float(loss_ref(bias)),
                               rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(jax.grad(loss_fused)(bias)),
        np.asarray(jax.grad(loss_ref)(bias)), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("temperature", [1.0, 2.0])
def test_kl_distill_parity_and_grads(temperature):
    """Chunked ensemble KL == naive kl_distill_loss (2 teachers with
    different hidden sizes, shifted mask, temperature), incl. the grad
    through the checkpointed chunk body. T=1024 per the round-2 verdict's
    'done' criterion, chunk smaller so several chunks run."""
    from dla_tpu.ops.fused_ce import fused_kl_distill_loss
    from dla_tpu.ops.losses import kl_distill_loss

    b, t, v = 2, 1024, 64
    rs = np.random.RandomState(10)
    hs = jnp.asarray(rs.randn(b, t, 12).astype(np.float32))
    sw = jnp.asarray(rs.randn(12, v).astype(np.float32) * 0.1)
    ht1 = jnp.asarray(rs.randn(b, t, 8).astype(np.float32))
    tw1 = jnp.asarray(rs.randn(8, v).astype(np.float32) * 0.1)
    ht2 = jnp.asarray(rs.randn(b, t, 20).astype(np.float32))
    tw2 = jnp.asarray(rs.randn(20, v).astype(np.float32) * 0.1)
    mask = jnp.asarray(
        np.concatenate([np.ones((b, t - 100)), np.zeros((b, 100))], 1),
        jnp.int32)

    def fused(hs, sw):
        return fused_kl_distill_loss(
            hs, sw, [ht1, ht2], [tw1, tw2], mask, temperature, chunk=256)

    def naive(hs, sw):
        return kl_distill_loss(
            hs @ sw, [ht1 @ tw1, ht2 @ tw2], mask, temperature)

    np.testing.assert_allclose(float(fused(hs, sw)), float(naive(hs, sw)),
                               rtol=1e-5)
    gf = jax.grad(fused, argnums=(0, 1))(hs, sw)
    gn = jax.grad(naive, argnums=(0, 1))(hs, sw)
    for a, b_ in zip(gf, gn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-6)


def test_model_level_parity():
    """hidden_states + fused CE == apply (logits) + materializing CE on a
    real (tiny) model, including the tied-embedding transpose path."""
    from dla_tpu.models.config import get_model_config
    from dla_tpu.models.transformer import Transformer

    for tie in (False, True):
        cfg = get_model_config("tiny")
        import dataclasses
        cfg = dataclasses.replace(cfg, tie_embeddings=tie)
        model = Transformer(cfg)
        params = model.init(jax.random.key(0))
        rs = np.random.RandomState(7)
        ids = jnp.asarray(rs.randint(1, 100, (2, 16)), jnp.int32)
        labels = jnp.where(ids % 5 == 0, -100, ids)

        def fused(p):
            h = model.hidden_states(p, ids)
            w, bias = model.unembed_params(p)
            return fused_cross_entropy_loss(h, w, labels, bias=bias,
                                            chunk=8)[0]

        def ref(p):
            return cross_entropy_loss(model.apply(p, ids), labels)[0]

        np.testing.assert_allclose(float(fused(params)), float(ref(params)),
                                   rtol=1e-5)
        gf = jax.grad(fused)(params)
        gr = jax.grad(ref)(params)
        for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gr)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-5)
