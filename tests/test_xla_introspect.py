"""XLA introspection (docs/OBSERVABILITY.md "XLA introspection"):
retrace attribution via argument fingerprints, per-fn cost/memory
gauges from the AOT path, live-HBM accounting, and the analytic
roofline + 6N cross-check.

THE pins: (a) an induced recompile produces a ``compile`` flight-
recorder event naming the changed argument ``old aval -> new aval`` and
increments ``telemetry/xla/recompiles``; a steady run attributes ZERO
recompiles with the trainer's trace-time compile counter pinned at 1,
(b) XLA's analytic FLOPs agree with the 6N estimate within the
documented tolerance on a pure-matmul step and every introspected fn
gets a roofline verdict, (c) the wrapper adds ZERO extra compiles — its
``lower()`` IS the one trace.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dla_tpu.telemetry import (
    FlightRecorder,
    IntrospectedFunction,
    MetricRegistry,
    MFUCalculator,
    is_catalog_name,
    live_array_bytes,
    register_live_bytes_gauge,
)
from dla_tpu.telemetry.mfu import ESTIMATE_TOLERANCE
from dla_tpu.telemetry.xla_introspect import (
    diff_fingerprints,
    fingerprint_args,
)


# ---------------------------------------------------------------------------
# fingerprints: what re-keys, what doesn't, and how changes are named
# ---------------------------------------------------------------------------

def test_fingerprint_diff_names_the_changed_arg_old_to_new():
    a = fingerprint_args(({"ids": np.zeros((8, 16), np.int32)},
                          np.float32(0.0)))
    b = fingerprint_args(({"ids": np.zeros((8, 32), np.int32)},
                          np.float32(0.0)))
    changes = diff_fingerprints(a, b)
    assert len(changes) == 1
    assert "ids" in changes[0]["arg"]
    assert changes[0]["old"] == "int32[8,16]"
    assert changes[0]["new"] == "int32[8,32]"


def test_fingerprint_ignores_values_keys_on_aval():
    """Traced scalars change value every step (guard EMA, fault
    injectors) and must never re-key the cache — mirroring jit."""
    a = fingerprint_args((np.float32(1.0), 3))
    b = fingerprint_args((np.float32(2.0), 7))
    assert a == b
    # but a python-scalar TYPE change is a retrace, and says so
    c = fingerprint_args((np.float32(1.0), 7.5))
    assert diff_fingerprints(a, c)[0]["new"] == "weak_float[]"


def test_fingerprint_structure_change_is_one_row():
    a = fingerprint_args(({"x": np.zeros(2)},))
    b = fingerprint_args(({"x": np.zeros(2), "y": np.zeros(2)},))
    changes = diff_fingerprints(a, b)
    assert len(changes) == 1 and "structure" in changes[0]["new"]


# ---------------------------------------------------------------------------
# the wrapper: zero extra compiles, attributed recompiles, fallback
# ---------------------------------------------------------------------------

def _wrapped(name="fn", **kw):
    """A jitted fn with a trace-time tick counter, wrapped."""
    ticks = []

    def f(x):
        ticks.append(1)              # ticks once per TRACE, not per call
        return jnp.sum(x * 2.0)

    return IntrospectedFunction(name, jax.jit(f), **kw), ticks


def test_wrapper_adds_zero_extra_compiles():
    fn, ticks = _wrapped()
    x = np.ones((4, 8), np.float32)
    outs = [float(fn(x)) for _ in range(5)]
    assert outs == [64.0] * 5        # results flow through untouched
    assert len(ticks) == 1           # the wrapper's lower() IS the trace
    assert fn.compiles == 1 and fn.recompiles == 0
    assert fn.last_event is None     # cache hit: nothing to attribute


def test_induced_recompile_emits_attributed_event_and_counters():
    reg = MetricRegistry()
    rec = FlightRecorder(capacity=32)
    seen = []
    fn, ticks = _wrapped("decode", registry=reg, recorder=rec,
                         on_compile=seen.append)
    fn.step = 3
    fn(np.ones((4, 8), np.float32))
    fn.step = 7
    fn(np.ones((4, 16), np.float32))          # induced: seq 8 -> 16
    assert len(ticks) == 2                    # same count plain jit pays
    assert fn.compiles == 2 and fn.recompiles == 1

    ev = fn.last_event
    assert ev is not None and ev["attributed"]
    assert ev["changed"][0]["old"] == "float32[4,8]"
    assert ev["changed"][0]["new"] == "float32[4,16]"

    # counters: the global rollup and the per-fn series
    snap = reg.snapshot()
    assert snap["telemetry/xla/recompiles"] == 1.0
    assert snap["telemetry/xla/decode/recompiles"] == 1.0
    assert is_catalog_name("telemetry/xla/recompiles")
    assert is_catalog_name("telemetry/xla/decode/recompiles")

    # the ring: first compile is marked first=True, the recompile names
    # the changed argument old -> new aval in human-readable text
    compiles = [e for e in rec.events if e["kind"] == "compile"]
    assert len(compiles) == 2
    assert compiles[0]["first"] and compiles[0]["step"] == 3
    assert "float32[4,8] -> float32[4,16]" in compiles[1]["changed"]
    assert compiles[1]["step"] == 7 and compiles[1]["attributed"]

    # on_compile forwarded the event (serving feeds anomaly from this);
    # first compiles never reach it
    assert len(seen) == 1 and seen[0]["step"] == 7


def test_cache_hit_after_recompile_leaves_last_event_none():
    fn, _ = _wrapped()
    a, b = np.ones((2, 4), np.float32), np.ones((2, 8), np.float32)
    fn(a)
    fn(b)
    assert fn.last_event is not None
    fn(a)                            # back to a cached specialization
    assert fn.last_event is None and fn.compiles == 2


def test_note_unattributed_compile_counts_and_records():
    reg = MetricRegistry()
    rec = FlightRecorder(capacity=8)
    fn, _ = _wrapped(registry=reg, recorder=rec)
    fn(np.ones((2, 2), np.float32))
    fn.note_unattributed_compile(step=11)
    ev = fn.last_event
    assert ev is not None and not ev["attributed"]
    assert reg.snapshot()["telemetry/xla/recompiles"] == 1.0
    ring = [e for e in rec.events if e["kind"] == "compile"
            and not e.get("first")]
    assert "unattributed" in ring[0]["changed"]
    assert ring[0]["step"] == 11


def test_disabled_wrapper_is_a_passthrough():
    fn, ticks = _wrapped(enabled=False)
    fn(np.ones((2, 2), np.float32))
    fn(np.ones((2, 4), np.float32))
    assert fn.compiles == 0 and fn.recompiles == 0
    assert len(ticks) == 2           # plain jit retraced, untouched


def test_aot_failure_falls_back_permanently_but_still_attributes():
    class BrokenJit:
        """Callable without .lower(): forces the fallback path."""
        def __init__(self):
            self.calls = 0

        def __call__(self, x):
            self.calls += 1
            return x

    raw = BrokenJit()
    fn = IntrospectedFunction("broken", raw)
    x = np.ones((2, 2), np.float32)
    assert fn(x) is x                # result still flows
    assert fn.fallback and "lower/compile failed" in fn.fallback_reason
    fn(np.ones((2, 4), np.float32))  # fingerprint diff still attributes
    assert fn.recompiles == 1 and fn.last_event["attributed"]
    assert raw.calls == 2


def test_cache_eviction_respects_max_entries():
    fn, ticks = _wrapped(max_entries=2)
    shapes = [(2, 2), (2, 4), (2, 8)]
    for s in shapes:
        fn(np.ones(s, np.float32))
    assert len(fn._cache) == 2
    assert len(ticks) == 3
    fn(np.ones((2, 2), np.float32))  # evicted: compiles again
    assert fn.compiles == 4


# ---------------------------------------------------------------------------
# cost/memory gauges, 6N cross-check, roofline — the analytic layer
# ---------------------------------------------------------------------------

def test_six_n_crosscheck_and_roofline_with_zero_extra_compiles():
    """Pin (b)+(c): a pure-matmul train step's XLA FLOPs agree with the
    6N estimate within ESTIMATE_TOLERANCE; the roofline verdict gauges
    publish; the in-body trace counter stays at 1 across repeat calls."""
    D, O, B = 64, 64, 32
    rs = np.random.RandomState(0)
    w = rs.normal(size=(D, O)).astype(np.float32)
    x = rs.normal(size=(B, D)).astype(np.float32)
    y = rs.normal(size=(B, O)).astype(np.float32)
    ticks = []

    def loss(w, x, y):
        ticks.append(1)
        return jnp.mean((x @ w - y) ** 2)

    mfu = MFUCalculator(D * O, device_kind="cpu", platform="cpu",
                        training=True)
    reg = MetricRegistry()
    fn = IntrospectedFunction("train_step",
                              jax.jit(jax.value_and_grad(loss)),
                              registry=reg, mfu_calc=mfu)
    for _ in range(4):
        fn(w, x, y)
    assert len(ticks) == 1 and fn.compiles == 1

    # fwd + bwd of one [B,D]x[D,O] matmul is 3 matmuls = 6*B*D*O FLOPs
    # = 6N per token: XLA's count differs only by elementwise epsilon
    assert fn.stats["flops"] > 0
    chk = mfu.check_estimate(fn.stats["flops"], tokens=B)
    assert chk["within_tolerance"] == 1.0, chk
    assert abs(chk["ratio"] - 1.0) <= ESTIMATE_TOLERANCE

    snap = reg.snapshot()
    for key in ("flops", "bytes_accessed", "roofline_intensity",
                "roofline_ridge", "roofline_compute_bound"):
        name = f"telemetry/xla/train_step/{key}"
        assert name in snap, name
        assert is_catalog_name(name), name
    assert snap["telemetry/xla/train_step/roofline_ridge"] > 0.0
    assert snap["telemetry/xla/train_step/roofline_compute_bound"] \
        in (0.0, 1.0)


def test_live_bytes_gauge_tracks_allocation():
    reg = MetricRegistry()
    register_live_bytes_gauge(reg)
    register_live_bytes_gauge(reg)   # idempotent per registry
    before = live_array_bytes()
    keep = jnp.ones((256, 256), jnp.float32)   # 256 KiB live
    after = reg.snapshot()["telemetry/xla/live_bytes"]
    assert after >= before + keep.nbytes
    del keep


# ---------------------------------------------------------------------------
# trainer integration: steady run = 1 compile, gauges + 6N in payload
# ---------------------------------------------------------------------------

def test_trainer_steady_run_one_compile_with_xla_gauges(mesh8, tmp_path):
    """Pin (a) steady-state: introspection ON adds zero compiles
    (train_step_compiles == 1, zero recompiles attributed) while the
    telemetry/xla/train_step/* gauges, live bytes, and the 6N ratio all
    surface in the registry."""
    from tests.test_telemetry import BatchIter, _make_trainer
    with jax.sharding.set_mesh(mesh8):
        tr = _make_trainer(mesh8, tmp_path / "run", max_steps=6,
                           log_every=2)
        assert tr.xla_introspect_enabled      # default-on
        it = BatchIter()
        tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        assert tr.step == 6
        assert tr.train_step_compiles == 1    # THE zero-extra-compile pin
        step_fn = tr._jit_train_step
        assert isinstance(step_fn, IntrospectedFunction)
        assert step_fn.compiles == 1 and step_fn.recompiles == 0
        assert not step_fn.fallback, step_fn.fallback_reason

        snap = tr.registry.snapshot()
        assert snap["telemetry/xla/train_step/flops"] > 0.0
        assert snap["telemetry/xla/train_step/bytes_accessed"] > 0.0
        assert snap["telemetry/xla/train_step/roofline_ridge"] > 0.0
        assert snap["telemetry/xla/live_bytes"] > 0.0
        # the 6N cross-check rode the log interval into the registry
        assert "telemetry/xla/train_step/flops_vs_6n_ratio" in snap
        assert "telemetry/xla/recompiles" not in snap \
            or snap["telemetry/xla/recompiles"] == 0.0


def test_trainer_introspection_off_switch(mesh8, tmp_path):
    from tests.test_telemetry import BatchIter, _make_trainer
    with jax.sharding.set_mesh(mesh8):
        tr = _make_trainer(mesh8, tmp_path / "run", max_steps=3,
                           telemetry={"xla_introspect":
                                      {"enabled": False}})
        it = BatchIter()
        tr.fit(it, rng=jax.random.key(0), data_state=it.state_dict)
        assert not tr.xla_introspect_enabled
        assert tr.train_step_compiles == 1
        assert not isinstance(tr._jit_train_step, IntrospectedFunction)
        assert "telemetry/xla/train_step/flops" not in \
            tr.registry.snapshot()
