"""Checkpointer tests: save/restore round-trip (sharded), latest pointer,
retention, numpy model loading."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dla_tpu.checkpoint import (
    Checkpointer,
    is_checkpoint_path,
    load_tree_numpy,
    resolve_checkpoint_dir,
)


def make_tree():
    return {
        "params": {
            "w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            "b": jnp.ones((8,), jnp.bfloat16),
        },
        "opt_state": {"count": jnp.zeros((), jnp.int32)},
    }


def test_roundtrip_plain(tmp_path):
    ck = Checkpointer(str(tmp_path / "ck"))
    tree = make_tree()
    ck.save(5, tree, aux={"note": "hi", "step": 5})
    got, aux = ck.restore(tree)
    assert aux["note"] == "hi"
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.dtype == b.dtype


def test_latest_pointer_and_retention(tmp_path):
    ck = Checkpointer(str(tmp_path / "ck"), keep_last_n=2)
    tree = make_tree()
    for s in (1, 2, 3):
        ck.save(s, tree)
    assert ck.latest_tag() == "step_00000003"
    dirs = sorted(d.name for d in (tmp_path / "ck").glob("step_*"))
    assert dirs == ["step_00000002", "step_00000003"]
    # 'latest' path resolution used by reference-style configs
    resolved = resolve_checkpoint_dir(tmp_path / "ck" / "latest")
    assert resolved.name == "step_00000003"
    assert is_checkpoint_path(tmp_path / "ck")
    assert is_checkpoint_path(tmp_path / "ck" / "latest")
    assert not is_checkpoint_path(tmp_path / "nope")


def test_restore_with_sharding(tmp_path, mesh8):
    ck = Checkpointer(str(tmp_path / "ck"))
    tree = make_tree()
    ck.save(1, tree)
    shardings = {
        "params": {
            "w": NamedSharding(mesh8, P("fsdp", "model")),
            "b": NamedSharding(mesh8, P()),
        },
        "opt_state": {"count": NamedSharding(mesh8, P())},
    }
    got, _ = ck.restore(tree, shardings=shardings)
    w = got["params"]["w"]
    assert w.sharding.spec == P("fsdp", "model")
    np.testing.assert_array_equal(np.asarray(w), np.asarray(tree["params"]["w"]))


def test_load_tree_numpy_prefix(tmp_path):
    ck = Checkpointer(str(tmp_path / "ck"))
    ck.save(2, make_tree(), aux={"model_config": {"x": 1}})
    params, aux = load_tree_numpy(tmp_path / "ck", prefix="params")
    assert set(params) == {"w", "b"}
    assert params["w"].shape == (8, 8)
    assert aux["model_config"] == {"x": 1}


def test_sharded_save_writes_per_shard_files(tmp_path, mesh8):
    """A sharded leaf must hit disk as one file per distinct index region
    (per-host shard I/O) — never as a gathered whole-array file."""
    from dla_tpu.parallel.sharding import shard_pytree

    ck = Checkpointer(str(tmp_path / "ck"))
    w = jnp.arange(16 * 8, dtype=jnp.float32).reshape(16, 8)
    tree = {"w": w, "scalar": jnp.zeros((), jnp.int32)}
    specs = {"w": P(("data", "fsdp"), "model"), "scalar": P()}
    sharded = shard_pytree(tree, specs, mesh8)
    out = ck.save(1, sharded)

    shard_files = sorted(f.name for f in out.glob("w-shard*.npy"))
    # mesh8 = data2 x fsdp2 x model2: 4 row-regions x 2 col-regions
    assert len(shard_files) == 8, shard_files
    assert not (out / "w.npy").exists()
    # replicated scalar still saved whole
    assert (out / "scalar.npy").exists()

    # restore without shardings assembles the full logical array
    got, _ = ck.restore({"w": w, "scalar": tree["scalar"]})
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(w))

    # numpy loading assembles too
    loaded, _ = load_tree_numpy(tmp_path / "ck")
    np.testing.assert_array_equal(loaded["w"], np.asarray(w))


def test_sharded_save_restores_onto_different_mesh(tmp_path, mesh8):
    """Cross-topology reshard: save on data2xfsdp2xmodel2, restore onto a
    pure-fsdp8 layout. Every device reads only its slice from shard files."""
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.parallel.sharding import shard_pytree

    ck = Checkpointer(str(tmp_path / "ck"))
    w = jnp.arange(16 * 8, dtype=jnp.bfloat16).reshape(16, 8)
    sharded = shard_pytree({"w": w}, {"w": P(("data", "fsdp"), "model")},
                           mesh8)
    ck.save(3, sharded)

    mesh_f = build_mesh(MeshConfig(data=1, fsdp=8, model=1, sequence=1))
    new_sharding = {"w": NamedSharding(mesh_f, P("fsdp", None))}
    got, _ = ck.restore({"w": w}, shardings=new_sharding)
    assert got["w"].sharding.spec == P("fsdp", None)
    assert got["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(got["w"], np.float32), np.asarray(w, np.float32))


@pytest.mark.parametrize("shrink", [4, 2])
def test_sharded_save_restores_onto_smaller_world(tmp_path, mesh8, shrink):
    """The elastic topology-shift resume path: a checkpoint written by
    an 8-device pod restores bit-identically onto a 4- or 2-device
    subset mesh (the survivors after a host loss). Each surviving
    device assembles its larger slice from the overlapping shard
    files; bf16 payloads come back bit-exact (no float round-trip)."""
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.parallel.sharding import shard_pytree

    ck = Checkpointer(str(tmp_path / "ck"))
    w = jnp.arange(16 * 8, dtype=jnp.bfloat16).reshape(16, 8)
    b = jnp.ones((8,), jnp.float32)
    sharded = shard_pytree({"w": w, "b": b},
                           {"w": P(("data", "fsdp"), "model"), "b": P()},
                           mesh8)
    ck.save(4, sharded, aux={"step": 4, "global_batch": 8})

    small = build_mesh(MeshConfig(data=1, fsdp=shrink, model=1, sequence=1),
                       devices=jax.devices()[:shrink])
    shardings = {"w": NamedSharding(small, P("fsdp", None)),
                 "b": NamedSharding(small, P())}
    got, aux = ck.restore({"w": w, "b": b}, shardings=shardings)
    assert aux["global_batch"] == 8       # the resume invariant rides aux
    assert got["w"].sharding.mesh.devices.size == shrink
    assert got["w"].sharding.spec == P("fsdp", None)
    assert got["w"].dtype == jnp.bfloat16
    # bit-identity, not just value equality
    assert np.asarray(got["w"]).tobytes() == np.asarray(w).tobytes()
    assert np.asarray(got["b"]).tobytes() == np.asarray(b).tobytes()


def test_format1_whole_file_restores_onto_sharded_mesh(tmp_path, mesh8):
    """A format-1 index (whole-file leaves, no ``shards`` list) is read
    as the one-shard case: pre-sharding checkpoints restore onto any
    mesh, each device slicing its region out of the whole file."""
    import json
    ck = Checkpointer(str(tmp_path / "ck"))
    w = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    out = ck.save(1, {"w": jnp.asarray(w)})
    # rewrite the index as format 1: whole-file leaf, no shard metadata
    idx = json.loads((out / "index.json").read_text())
    assert idx["format"] == 2
    for meta in idx["leaves"].values():
        meta.pop("shards", None)
        meta["file"] = meta.get("file", "w.npy")
    idx["format"] = 1
    (out / "index.json").write_text(json.dumps(idx))

    shardings = {"w": NamedSharding(mesh8, P(("data", "fsdp"), "model"))}
    got, _ = ck.restore({"w": jnp.asarray(w)}, shardings=shardings)
    assert got["w"].sharding.spec == P(("data", "fsdp"), "model")
    np.testing.assert_array_equal(np.asarray(got["w"]), w)


def test_overwrite_same_step(tmp_path):
    ck = Checkpointer(str(tmp_path / "ck"))
    t1 = make_tree()
    ck.save(1, t1, tag="final")
    t2 = jax.tree.map(lambda x: x + 1, t1)
    ck.save(1, t2, tag="final")
    got, _ = ck.restore(t1, tag="final")
    np.testing.assert_array_equal(
        np.asarray(got["params"]["w"]), np.asarray(t2["params"]["w"]))


def test_restore_adapts_layer_stack_layout(tmp_path, mesh8):
    """A checkpoint saved with flat [L, ...] layer leaves restores into
    an interleaved-storage template ([V, S, c, ...]) by row-major
    reshape — pre-layout-change checkpoints stay resumable (round 5),
    and stage-count changes are a free reshape. (Size-mismatched leaves
    keep restore's longstanding behavior: saved shape wins — the
    adaptation only engages on equal element counts.)"""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dla_tpu.checkpoint.checkpointer import Checkpointer
    ck = Checkpointer(tmp_path / "ck")
    flat = {"layers": {"wq": np.arange(4 * 6, dtype=np.float32
                                       ).reshape(4, 2, 3)}}
    ck.save(1, flat, {"step": 1})
    tmpl = {"layers": {"wq": np.zeros((2, 2, 1, 2, 3), np.float32)}}
    sh = {"layers": {"wq": NamedSharding(mesh8, P(None, "data"))}}
    tree, aux = ck.restore(tmpl, shardings=sh)
    got = np.asarray(tree["layers"]["wq"])
    assert got.shape == (2, 2, 1, 2, 3)
    # row-major invariant: flattening recovers the canonical order
    np.testing.assert_array_equal(got.reshape(4, 2, 3),
                                  flat["layers"]["wq"])
