"""Checkpointer tests: save/restore round-trip (sharded), latest pointer,
retention, numpy model loading."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dla_tpu.checkpoint import (
    Checkpointer,
    is_checkpoint_path,
    load_tree_numpy,
    resolve_checkpoint_dir,
)


def make_tree():
    return {
        "params": {
            "w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            "b": jnp.ones((8,), jnp.bfloat16),
        },
        "opt_state": {"count": jnp.zeros((), jnp.int32)},
    }


def test_roundtrip_plain(tmp_path):
    ck = Checkpointer(str(tmp_path / "ck"))
    tree = make_tree()
    ck.save(5, tree, aux={"note": "hi", "step": 5})
    got, aux = ck.restore(tree)
    assert aux["note"] == "hi"
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.dtype == b.dtype


def test_latest_pointer_and_retention(tmp_path):
    ck = Checkpointer(str(tmp_path / "ck"), keep_last_n=2)
    tree = make_tree()
    for s in (1, 2, 3):
        ck.save(s, tree)
    assert ck.latest_tag() == "step_00000003"
    dirs = sorted(d.name for d in (tmp_path / "ck").glob("step_*"))
    assert dirs == ["step_00000002", "step_00000003"]
    # 'latest' path resolution used by reference-style configs
    resolved = resolve_checkpoint_dir(tmp_path / "ck" / "latest")
    assert resolved.name == "step_00000003"
    assert is_checkpoint_path(tmp_path / "ck")
    assert is_checkpoint_path(tmp_path / "ck" / "latest")
    assert not is_checkpoint_path(tmp_path / "nope")


def test_restore_with_sharding(tmp_path, mesh8):
    ck = Checkpointer(str(tmp_path / "ck"))
    tree = make_tree()
    ck.save(1, tree)
    shardings = {
        "params": {
            "w": NamedSharding(mesh8, P("fsdp", "model")),
            "b": NamedSharding(mesh8, P()),
        },
        "opt_state": {"count": NamedSharding(mesh8, P())},
    }
    got, _ = ck.restore(tree, shardings=shardings)
    w = got["params"]["w"]
    assert w.sharding.spec == P("fsdp", "model")
    np.testing.assert_array_equal(np.asarray(w), np.asarray(tree["params"]["w"]))


def test_load_tree_numpy_prefix(tmp_path):
    ck = Checkpointer(str(tmp_path / "ck"))
    ck.save(2, make_tree(), aux={"model_config": {"x": 1}})
    params, aux = load_tree_numpy(tmp_path / "ck", prefix="params")
    assert set(params) == {"w", "b"}
    assert params["w"].shape == (8, 8)
    assert aux["model_config"] == {"x": 1}


def test_overwrite_same_step(tmp_path):
    ck = Checkpointer(str(tmp_path / "ck"))
    t1 = make_tree()
    ck.save(1, t1, tag="final")
    t2 = jax.tree.map(lambda x: x + 1, t1)
    ck.save(1, t2, tag="final")
    got, _ = ck.restore(t1, tag="final")
    np.testing.assert_array_equal(
        np.asarray(got["params"]["w"]), np.asarray(t2["params"]["w"]))
