"""Headline benchmark: SFT training throughput, tokens/sec/chip.

Prints ONE JSON line:
  {"metric": "sft_tokens_per_sec_per_chip", "value": N, "unit": "tok/s/chip",
   "vs_baseline": R}

``vs_baseline`` normalizes against the north-star target (BASELINE.md:
>= 0.8x the per-device throughput of the 8xH100 NCCL reference stack).
Neither repo publishes absolute H100 numbers (SURVEY.md sec 6), so the
comparison is made in hardware-normalized terms: a well-tuned
DeepSpeed-ZeRO3 run sustains ~40% MFU on H100-class hardware, so the
baseline per-chip token rate on *this* chip class is
0.8 * 0.40 * peak_flops / (6 * n_params) and

  vs_baseline = measured_MFU / (0.8 * 0.40)

i.e. vs_baseline >= 1.0 means this framework beats 0.8x the H100 baseline
after normalizing for per-chip peak FLOPs.

Robustness contract: the bench PREFERS the real accelerator, falls back
to forced CPU when no accelerator comes up, and emits its JSON line with
exit code 0 on EVERY path. Backend init through the TPU tunnel has been
observed to *hang* (not raise) — so the parent process NEVER initializes
jax itself: every jax touch happens in a bounded child. The ladder is:

  1. PROBE child (DLA_BENCH_PROBE_TIMEOUT, default 180s): devices-up +
     one tiny jit, nothing else. The budget is sized ~4x the healthy
     tunnel's observed cold-init time (tens of seconds) so a slow but
     healthy init is not misclassified as a wedge, while a real wedge
     costs ~180s instead of a 900s compile+measure budget (round-3
     post-mortem: one wedged 900s rung ate the driver's window before
     the CPU fallback could run).
  2. Accelerator measure children, a descent ladder over micro batch
     sizes (8 -> 6 -> 4, or just the operator-set DLA_BENCH_MICRO),
     each in a FRESH child because an HBM OOM can poison a live TPU
     client; a child that times out or reports no backend ends the
     ladder immediately.
  3. Forced-CPU child guarantees the line.

Worst case wall time is DLA_BENCH_PROBE_TIMEOUT (wedged tunnel) +
DLA_BENCH_CPU_TIMEOUT (default 600s); healthy-tunnel worst case adds
len(ladder) * DLA_BENCH_ACCEL_TIMEOUT (default 900s each).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))

# Per-chip peak FLOPs / HBM-bandwidth tables live in
# dla_tpu.telemetry.mfu (ONE set of peak numbers for bench, the
# trainer's MFU gauge, and the sweep tools). Imported lazily inside the
# lookup helpers: importing the dla_tpu package pulls in the jax module,
# and this parent process must stay jax-free (backend init can hang).
BASELINE_MFU = 0.8 * 0.40  # 0.8x of a 40%-MFU H100-class DeepSpeed baseline
# PPO baseline efficiency factors (BASELINE.md "PPO vs_baseline"): an
# H100-class trl/DeepSpeed rollout+update loop modeled at 40% MFU on the
# compute-bound phases (scoring forwards, update fwd+bwd) and 60% of HBM
# bandwidth on the decode phase — generous to the baseline: the
# reference's actual loop host-bounces between decode and scoring
# (src/training/train_rlhf.py:123-147) and uses HF generate.
PPO_BASELINE_MFU = 0.40
PPO_BASELINE_BW_EFF = 0.60


def hbm_bw(device) -> float:
    """Per-chip HBM bandwidth for the roofline. Unrecognized accelerator
    kinds fall back to the v5e figure — LOUDLY (ADVICE r4): a silently
    assumed bandwidth would skew decode rooflines and PPO vs_baseline on
    future chips with no trace in the artifact. hbm_bw_assumed() tells
    callers to record the fallback in their emitted detail."""
    bw, assumed = _hbm_bw_lookup(device)
    if assumed:
        print(f"[bench] WARNING: unrecognized device_kind "
              f"'{getattr(device, 'device_kind', '?')}' — assuming v5e "
              f"HBM bandwidth ({bw:.3g} B/s) for the roofline",
              file=sys.stderr)
    return bw


def hbm_bw_assumed(device) -> bool:
    """True when hbm_bw() is a fallback guess, not a known-chip figure."""
    return _hbm_bw_lookup(device)[1]


def _hbm_bw_lookup(device):
    from dla_tpu.telemetry.mfu import hbm_bw_for
    return hbm_bw_for(getattr(device, "device_kind", "cpu"),
                      device.platform)


def ppo_baseline_samples_per_sec(n_params: int, batch: int, prompt: int,
                                 new_tokens: int, peak: float, bw: float,
                                 lora: bool, epochs: int = 1) -> float:
    """Hardware-normalized PPO rollout+update baseline, samples/s/chip.

    Per-sample cost model of the reference loop's phases on THIS chip
    with H100-class efficiency (the PPO analog of the SFT MFU bar):
      decode  — bandwidth-bound: new_tokens param reads amortized over
                the rollout batch,
      score   — 3 forwards (policy logp, ref logp, RM) at 2*N FLOPs/tok,
      update  — fwd+bwd at 6*N FLOPs/tok (4*N with LoRA: no base dW).
    """
    total_len = prompt + new_tokens
    p_bytes = 2.0 * n_params  # bf16 weights
    decode_s = new_tokens * p_bytes / (PPO_BASELINE_BW_EFF * bw * batch)
    score_s = 3 * 2.0 * n_params * total_len / (PPO_BASELINE_MFU * peak)
    upd_factor = 4.0 if lora else 6.0
    update_s = (upd_factor * n_params * total_len * epochs
                / (PPO_BASELINE_MFU * peak))
    return 1.0 / (decode_s + score_s + update_s)


def peak_flops(device) -> float:
    from dla_tpu.telemetry.mfu import peak_flops_for
    return peak_flops_for(getattr(device, "device_kind", "cpu"),
                          device.platform)


def count_params(params) -> int:
    import jax
    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(params)))


def _try_devices(retries: int = 2, delay_s: float = 5.0):
    """Initialize the jax backend, retrying transient failures (the TPU
    tunnel can return UNAVAILABLE on first contact). Returns the device
    list or None if no backend ever comes up. May HANG on a wedged
    tunnel — which is why this only ever runs inside a child process
    whose lifetime the parent bounds."""
    import jax
    last = None
    for attempt in range(retries):
        try:
            return jax.devices()
        except Exception as e:  # backend init failed; retry
            last = e
            print(f"[bench] backend init attempt {attempt + 1}/{retries} "
                  f"failed: {type(e).__name__}: {e}", file=sys.stderr)
            time.sleep(delay_s)
    print(f"[bench] no accelerator backend: {last}", file=sys.stderr)
    return None


def run_probe() -> dict:
    """Tunnel-health probe: devices up + one tiny jit. Cheap enough that
    a wedged tunnel only burns the probe timeout, not a measure budget."""
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    x = jnp.ones((128, 128), jnp.bfloat16)
    jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    return {"metric": "probe", "value": 1, "unit": "ok",
            "detail": {"platform": dev.platform,
                       "device_kind": dev.device_kind,
                       "n_devices": jax.device_count()}}


def run_bench() -> dict:
    """The measurement itself. Assumes a live jax backend."""
    import jax
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.fused_ce import model_fused_ce
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.training.trainer import Trainer

    on_accel = jax.devices()[0].platform != "cpu"
    if on_accel:
        # ~350M-param Mistral-style decoder (GQA 8q/4kv like Mistral-7B's
        # 32q/8kv ratio, head_dim 128): big enough to exercise the MXU,
        # small enough that params + Adam state fit one v5e chip.
        # Measured-fastest single-chip configuration (round-5 on-chip
        # sweep, tools/sweep_bench.py): Pallas flash attention with
        # 1024x1024 blocks, remat="dots", micro=8, fused CE at
        # chunk=4096, bf16 Adam first moment — 33.0k tok/s (35.0% MFU,
        # 1.094x the H100-normalized bar). head_dim 64 -> 128 was the
        # big rock (round 3): it fills the MXU's 128-deep contraction in
        # the attention kernel AND stops the saved flash activations
        # from 2x lane-padding. Round 5 added the block-size bump
        # (1024-blocks cut the causal diagonal waste and per-block
        # bookkeeping vs 512: +3.9% step) and the larger CE chunk
        # (fewer [chunk, V] logit tiles: +3.1%); combined +6%.
        cfg = ModelConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_layers=24, num_heads=8, num_kv_heads=4,
            max_seq_length=2048, remat="dots", attention="flash",
            flash_block_q=1024, flash_block_k=1024)
        try:
            micro = int(os.environ.get("DLA_BENCH_MICRO", "8"))
        except ValueError:
            micro = 8
        seq, steps, warmup = 2048, 6, 2
    else:  # CPU fallback so the bench always emits its line
        cfg = ModelConfig(
            vocab_size=512, hidden_size=128, intermediate_size=384,
            num_layers=4, num_heads=8, num_kv_heads=8,
            max_seq_length=256, remat="none", dtype="float32",
            param_dtype="float32")
        micro, seq, steps, warmup = 2, 256, 4, 1

    print(f"[bench] devices up: {jax.devices()[0].device_kind} "
          f"x{jax.device_count()}", file=sys.stderr)
    mesh = build_mesh(MeshConfig(data=1, fsdp=-1, model=1, sequence=1))
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    jax.block_until_ready(params)
    n_params = count_params(params)
    print(f"[bench] params initialized: {n_params / 1e6:.0f}M",
          file=sys.stderr)

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        loss, _ = model_fused_ce(model, p, batch,
                                 **({"chunk": 4096} if on_accel else {}))
        return loss, {}

    config = {
        "experiment_name": "bench",
        "optimization": {
            "total_batch_size": micro * mesh.devices.size,
            "micro_batch_size": micro, "learning_rate": 1e-4,
            "max_train_steps": steps, "lr_scheduler": "constant",
            "max_grad_norm": 1.0,
            # bf16 first moment frees ~0.7G for the micro=8 batch
            "adam_moment_dtype": "bfloat16",
        },
        "logging": {"output_dir": "/tmp/dla_bench_ckpt", "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    with jax.sharding.set_mesh(mesh):
        trainer = Trainer(config=config, mesh=mesh, loss_fn=loss_fn,
                          params=params, param_specs=model.partition_specs())
        rs = np.random.RandomState(0)
        local_bs = micro * mesh.devices.size
        batch = {
            "input_ids": rs.randint(1, cfg.vocab_size, (local_bs, seq)
                                    ).astype(np.int32),
            "attention_mask": np.ones((local_bs, seq), np.int32),
            "labels": rs.randint(1, cfg.vocab_size, (local_bs, seq)
                                 ).astype(np.int32),
        }
        t_c = time.perf_counter()
        for i in range(warmup):
            trainer.step_on_batch(batch, jax.random.key(i))
        print(f"[bench] warmup ({warmup} steps incl. compile): "
              f"{time.perf_counter() - t_c:.1f}s", file=sys.stderr)
        t0 = time.perf_counter()
        for i in range(steps):
            trainer.step_on_batch(batch, jax.random.key(100 + i))
        dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    tokens = local_bs * seq * steps
    tok_s_chip = tokens / dt / n_chips
    mfu = tok_s_chip * 6 * n_params / peak_flops(jax.devices()[0])
    vs_baseline = mfu / BASELINE_MFU
    return {
        "metric": "sft_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(vs_baseline, 4),
        # which ladder rung / platform produced this number — a degraded
        # micro=4 fallback or the forced-CPU fallback (wedged tunnel)
        # must be distinguishable from the tuned TPU micro=8 config
        "detail": {"micro": micro, "seq": seq,
                   "params_m": round(n_params / 1e6),
                   "mfu": round(mfu, 4),
                   "platform": jax.devices()[0].device_kind},
    }


def run_ppo_bench() -> dict:
    """PPO rollout+update throughput, samples/sec — the second north-star
    metric BASELINE.json names ('PPO rollout+update samples/sec @7B'),
    measured at representative scale: a ~1.3B-param policy with LoRA
    adapters (the HBM-fitting RLHF setup: frozen bf16 base ALIASED as
    the reference model — one tree serves both — plus a 1.3B reward
    model), jitted scan-decode rollout over merged weights, on-device
    reinforce update of the adapters. vs_baseline normalizes against an
    H100-class trl/DeepSpeed loop modeled on this chip's peak specs
    (ppo_baseline_samples_per_sec)."""
    import jax
    import jax.numpy as jnp
    from dla_tpu.generation.engine import GenerationConfig, build_generate_fn
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.reward import RewardModel
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.parallel.sharding import sharding_tree
    from dla_tpu.training.train_rlhf import (
        make_policy_gradient_loss,
        make_score_fn,
    )
    from dla_tpu.training.trainer import Trainer

    on_accel = jax.devices()[0].platform != "cpu"
    if on_accel:
        # ~1.3B llama-shaped policy (2048 x 24L, GQA 16q/8kv, hd 128).
        # bf16 base (frozen, shared policy/ref) + bf16 RM + one merged
        # rollout copy + KV cache ~ 9.5G of a v5e's 16G HBM.
        cfg = ModelConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=24, num_heads=16, num_kv_heads=8,
            max_seq_length=512, remat="dots", attention="flash",
            param_dtype="bfloat16", lora_r=16,
            # int8 KV cache halves the rollout's cache HBM traffic
            # (~38% of decode bytes at this batch/seq)
            kv_cache_dtype="int8")
        # rollout batch 64 = the reference's own scale
        # (config/rlhf_config.yaml rollout_batch_size)
        batch, prompt_w, new_tokens, rollouts, warmup = 64, 128, 128, 3, 1
        # the UPDATE phase grad-accumulates 4 x 16 rows: at micro=64 the
        # "dots" remat stash is [24L, 64, 256, 5632] bf16 x2 (~8.2G) and
        # the step OOMs a 15.75G v5e (measured r5); micro=16 bounds the
        # stash at ~2.1G with the same samples/sec semantics
        update_micro, update_accum = 16, 4
    else:
        cfg = ModelConfig(
            vocab_size=512, hidden_size=64, intermediate_size=192,
            num_layers=2, num_heads=4, num_kv_heads=4,
            max_seq_length=128, remat="none", dtype="float32",
            param_dtype="float32", lora_r=4)
        batch, prompt_w, new_tokens, rollouts, warmup = 4, 16, 16, 2, 1
        update_micro, update_accum = batch, 1

    mesh = build_mesh(MeshConfig(data=1, fsdp=-1, model=1, sequence=1))
    policy = Transformer(cfg)
    rm = RewardModel(cfg)
    with jax.sharding.set_mesh(mesh):
        specs = policy.partition_specs()
        base = jax.device_put(policy.init(jax.random.key(0)),
                              sharding_tree(specs, mesh))
        adapters = policy.init_lora(jax.random.key(1))
        rm_params = jax.device_put(
            rm.init(jax.random.key(2)),
            sharding_tree(rm.partition_specs(), mesh))
        from dla_tpu.parallel.mesh import data_parallel_size
        dp = data_parallel_size(mesh)
        config = {
            "experiment_name": "bench_ppo",
            "optimization": {
                "total_batch_size": batch,
                "micro_batch_size": max(1, update_micro // dp),
                "learning_rate": 1e-6, "max_train_steps": rollouts + warmup,
                "lr_scheduler": "constant", "max_grad_norm": 1.0,
            },
            "logging": {"output_dir": "/tmp/dla_bench_ppo", "log_dir": None},
            "hardware": {"gradient_accumulation_steps": update_accum},
        }
        trainer = Trainer(
            config=config, mesh=mesh,
            loss_fn=make_policy_gradient_loss(policy, "reinforce", 0.2,
                                              lora=True),
            params=adapters, param_specs=policy.lora_partition_specs(),
            frozen={"base": base}, frozen_specs={"base": specs})
        gen = GenerationConfig(max_new_tokens=new_tokens, do_sample=True,
                               temperature=1.0, top_p=1.0,
                               eos_token_id=-1, pad_token_id=0)
        generate_fn = jax.jit(build_generate_fn(policy, gen))
        # ref == frozen base (LoRA aliasing, train_rlhf.py:283-285)
        score_fn = make_score_fn(policy, policy, rm)
        merge_fn = jax.jit(policy.merge_lora)
        # int8 weight-only rollouts: halves the decode loop's dominant
        # HBM traffic (ppo.rollout_quantize_weights in the trainer)
        quant_fn = jax.jit(policy.quantize_weights)

        rs = np.random.RandomState(0)
        ids = rs.randint(1, cfg.vocab_size, (batch, prompt_w)).astype(np.int32)
        mask = np.ones((batch, prompt_w), np.int32)
        ids_d = jax.device_put(jnp.asarray(ids))
        mask_d = jax.device_put(jnp.asarray(mask))

        def one_rollout(i):
            merged = quant_fn(merge_fn(base, trainer.params))
            out = generate_fn(merged, ids_d, mask_d, jax.random.key(i))
            scores = score_fn(merged, base, rm_params,
                              out["sequences"], out["sequence_mask"],
                              jnp.float32(0.1))
            up = {"sequences": out["sequences"],
                  "sequence_mask": out["sequence_mask"],
                  "advantages": scores["advantages"],
                  "behavior_logp": scores["behavior_logp"]}
            trainer.step_on_device_batch(up, jax.random.key(100 + i))

        for i in range(warmup):
            one_rollout(i)
        t0 = time.perf_counter()
        for i in range(rollouts):
            one_rollout(10 + i)
        dt = time.perf_counter() - t0

    n_params = count_params(base)
    samples_s = batch * rollouts / dt / jax.device_count()
    dev = jax.devices()[0]
    baseline = ppo_baseline_samples_per_sec(
        n_params, batch, prompt_w, new_tokens,
        peak_flops(dev), hbm_bw(dev), lora=True)
    return {
        "metric": "ppo_rollout_update_samples_per_sec_per_chip",
        "value": round(samples_s, 3),
        "unit": "samples/s/chip",
        "vs_baseline": round(samples_s / (0.8 * baseline), 4),
        "detail": {"batch": batch, "prompt_len": prompt_w,
                   "new_tokens": new_tokens, "lora_r": cfg.lora_r,
                   "rollout_weights": "int8", "kv_cache": cfg.kv_cache_dtype,
                   "params_m": round(n_params / 1e6),
                   "baseline_samples_s_chip": round(baseline, 2),
                   "platform": dev.device_kind,
                   # flag a guessed roofline bandwidth (ADVICE r4) so
                   # artifact consumers can spot a mismatched baseline
                   **({"hbm_bw_assumed_v5e": True}
                      if hbm_bw_assumed(dev) else {})},
    }


def run_decode_bench() -> dict:
    """Autoregressive decode ms/token through the KV-cache engine (the
    PPO rollout hot path; reference only measured forward passes,
    src/eval/eval_latency.py:22-63)."""
    import jax
    from dla_tpu.eval.eval_latency import measure_decode
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer

    on_accel = jax.devices()[0].platform != "cpu"
    if on_accel:
        # bf16 KV: the r5 on-chip sweep measured int8 KV ALONE as a
        # regression at this scale (1.655 vs 1.45 ms/token — dequant
        # work outweighs bandwidth savings while the cache is small
        # next to the weights; it pays only combined with int8 weights,
        # tools/sweep_decode.py b8_w8kv8 = 1.23 ms)
        # bf16 params: the inference/rollout storage dtype (fp32
        # masters would double the per-step weight read — same
        # rationale as tools/sweep_decode.py, review r4)
        cfg = ModelConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_layers=24, num_heads=8, num_kv_heads=4,
            max_seq_length=2048, attention="flash", remat="none",
            dtype="bfloat16", param_dtype="bfloat16")
        b, prompt, new = 8, 128, 256
    else:
        cfg = ModelConfig(
            vocab_size=512, hidden_size=64, intermediate_size=192,
            num_layers=2, num_heads=4, num_kv_heads=4,
            max_seq_length=128, remat="none", dtype="float32",
            param_dtype="float32")
        b, prompt, new = 2, 16, 16
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    row = measure_decode(model, params, b, prompt, new)
    return {
        "metric": "decode_ms_per_token",
        "value": round(row["ms_per_token"], 3),
        "unit": "ms/token",
        "detail": {"batch": b, "prompt_len": prompt, "new_tokens": new,
                   "decode_tok_s_chip": round(
                       row["decode_tokens_per_second_per_chip"], 1),
                   "params_m": round(count_params(params) / 1e6)},
    }


def run_serving_bench() -> dict:
    """Continuous-batching serving throughput: requests/s and TTFT/ITL
    percentiles under a Poisson arrival trace through the paged-KV
    engine (dla_tpu/serving) — the rollout-side counterpart of the
    decode bench's fixed-batch ms/token."""
    import jax
    from dla_tpu.eval.eval_latency import measure_serving
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer

    on_accel = jax.devices()[0].platform != "cpu"
    if on_accel:
        cfg = ModelConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_layers=24, num_heads=8, num_kv_heads=4,
            max_seq_length=2048, attention="flash", remat="none",
            dtype="bfloat16", param_dtype="bfloat16")
        srv = {"num_requests": 32, "arrival_rate": 32.0, "new_tokens": 64,
               "prompt_len_min": 32, "prompt_len_max": 128,
               "page_size": 16, "num_pages": 512, "num_slots": 8,
               "max_model_len": 256, "max_prefill_batch": 4}
    else:
        cfg = ModelConfig(
            vocab_size=512, hidden_size=64, intermediate_size=192,
            num_layers=2, num_heads=4, num_kv_heads=4,
            max_seq_length=128, remat="none", dtype="float32",
            param_dtype="float32")
        srv = {"num_requests": 6, "arrival_rate": 100.0, "new_tokens": 8,
               "prompt_len_min": 4, "prompt_len_max": 16,
               "page_size": 4, "num_pages": 64, "num_slots": 2,
               "max_model_len": 32, "max_prefill_batch": 2}
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    row = measure_serving(model, params, srv)
    return {
        "metric": "serving_requests_per_s",
        "value": round(row["requests_per_second"], 3),
        "unit": "req/s",
        "detail": {"requests_per_s": round(row["requests_per_second"], 3),
                   "ttft_ms_p50": round(row["ttft_ms_p50"], 2),
                   "ttft_ms_p95": round(row["ttft_ms_p95"], 2),
                   "itl_ms_p50": round(row["itl_ms_p50"], 3),
                   "page_occupancy": round(row["page_occupancy_peak"], 4),
                   "serve_tok_s": round(row["serve_tokens_per_second"], 1),
                   "preemptions": int(row["preemptions"]),
                   "num_slots": row["num_slots"],
                   "num_requests": row["num_requests"],
                   "arrival_rate": row["arrival_rate"],
                   "params_m": round(count_params(params) / 1e6)},
    }


def run_serving_prefix_bench() -> dict:
    """Shared-prefix serving A/B: the same K-families x N-requests trace
    through the chunked-prefill engine with the prefix cache on vs off.
    The headline is the fraction of prefill tokens the cache saved
    (higher is better); detail carries both arms' ITL p95 and the greedy
    bit-identity check — a caching regression shows up as a saved-frac
    drop or an outputs_identical flip, both gateable."""
    import jax
    from dla_tpu.eval.eval_latency import measure_shared_prefix
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer

    on_accel = jax.devices()[0].platform != "cpu"
    if on_accel:
        cfg = ModelConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_layers=24, num_heads=8, num_kv_heads=4,
            max_seq_length=2048, attention="flash", remat="none",
            dtype="bfloat16", param_dtype="bfloat16")
        srv = {"arrival_rate": 64.0, "new_tokens": 32,
               "page_size": 16, "num_pages": 1024, "num_slots": 8,
               "max_model_len": 256,
               "chunked_prefill": {"chunk": 32},
               "shared_prefix": {"families": 8, "requests_per_family": 16,
                                 "prefix_len": 96, "suffix_len": 16}}
    else:
        cfg = ModelConfig(
            vocab_size=512, hidden_size=64, intermediate_size=192,
            num_layers=2, num_heads=4, num_kv_heads=4,
            max_seq_length=128, remat="none", dtype="float32",
            param_dtype="float32")
        srv = {"arrival_rate": 1000.0, "new_tokens": 4,
               "page_size": 4, "num_pages": 96, "num_slots": 4,
               "max_model_len": 32,
               "chunked_prefill": {"chunk": 8},
               "shared_prefix": {"families": 4, "requests_per_family": 6,
                                 "prefix_len": 16, "suffix_len": 4}}
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    row = measure_shared_prefix(model, params, srv)
    return {
        "metric": "serving_prefill_tokens_saved_frac",
        "value": round(row["prefill_tokens_saved_frac"], 4),
        "unit": "frac",
        "detail": {
            "cache_hit_rate": round(row["cache_hit_rate"], 4),
            "outputs_identical": bool(row["outputs_identical"]),
            "itl_ms_p95_cache_on": round(row["itl_ms_p95_cache_on"], 3),
            "itl_ms_p95_cache_off": round(row["itl_ms_p95_cache_off"], 3),
            "ttft_ms_p95_cache_on": round(row["ttft_ms_p95_cache_on"], 2),
            "ttft_ms_p95_cache_off": round(
                row["ttft_ms_p95_cache_off"], 2),
            "cache_evictions": int(row["cache_evictions"]),
            "families": row["families"],
            "requests_per_family": row["requests_per_family"],
            "prefix_len": row["prefix_len"],
            "suffix_len": row["suffix_len"],
            "prefill_chunk": row["prefill_chunk"],
            "params_m": round(count_params(params) / 1e6)},
    }


def run_rollout_bench() -> dict:
    """Disaggregated-rollout A/B on a long-tail response-length mix:
    slot-steps per generated token through the serving-engine rollout
    path (dla_tpu/rollout — continuous batching retires short rows
    early and refills their slots) vs the fixed-shape batch generate
    path (every row pays decode steps until the LONGEST row finishes).
    The headline is the padding waste recovered, ``1 - serving/batch``
    (higher is better); the batch arm's cost is exact by construction
    (rows x longest row — eos is disabled so every row runs its full
    per-row budget), the serving arm's decode steps are measured.
    Deterministic, CPU-sized, in-process."""
    import jax
    import numpy as np
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.sampling import derive_rollout_seeds
    from dla_tpu.rollout import RolloutEngine
    from dla_tpu.serving import ServingConfig

    cfg = ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=192,
        num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_length=128, remat="none", dtype="float32",
        param_dtype="float32")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    # long-tail budgets: most rows are short, one dominates — the shape
    # that makes fixed-batch padding waste worst
    max_new = [3, 3, 3, 4, 4, 6, 8, 24]
    rows, longest = len(max_new), max(max_new)
    gen = GenerationConfig(max_new_tokens=longest, do_sample=True,
                           temperature=1.0, eos_token_id=-1,
                           pad_token_id=0)
    rs = np.random.RandomState(7)
    lens = rs.randint(4, 11, (rows,))
    width = int(lens.max())
    ids = np.zeros((rows, width), np.int32)
    mask = np.zeros_like(ids)
    for i, n in enumerate(lens):
        ids[i, :n] = rs.randint(3, 500, (n,))
        mask[i, :n] = 1
    num_slots = 4
    eng = RolloutEngine(
        model, params, gen,
        ServingConfig(page_size=4, num_pages=96, num_slots=num_slots,
                      max_model_len=48, max_prefill_batch=2))
    out = eng.generate(ids, mask, derive_rollout_seeds(11, rows),
                       max_new=max_new)
    snap = eng.metrics.snapshot()
    decode_steps = eng._decode_steps_total()
    eng.close()
    tokens = int(np.asarray(out["response_mask"]).sum())
    assert tokens == sum(max_new), "eos disabled: budgets run in full"
    serving_spt = decode_steps * num_slots / tokens
    batch_spt = rows * longest / tokens
    recovered = 1.0 - serving_spt / batch_spt
    return {
        "metric": "rollout_padding_waste_recovered",
        "value": round(recovered, 4),
        "unit": "frac",
        "detail": {
            "padding_waste_recovered": round(recovered, 4),
            "serving_slot_steps_per_token": round(serving_spt, 4),
            "batch_slot_steps_per_token": round(batch_spt, 4),
            "serving_decode_steps": decode_steps,
            "gen_tokens_per_s": round(snap["rollout/gen_tokens_per_s"], 1),
            "tokens": tokens,
            "rows": rows,
            "num_slots": num_slots,
            "longest_row": longest,
            "params_m": round(count_params(params) / 1e6)},
    }


def run_rollout_fleet_bench() -> dict:
    """Elastic sampler-fleet A/B (dla_tpu/rollout/actor_fleet), three
    measurements in one row: (1) refit fanout at N=4 — every member
    publish costs a fixed ``refit_delay_s``, so the serial baseline
    pays ~N delays while the broadcast tree pays ~wave-count (2 at
    branch 2); the headline is that wall-time ratio (higher is
    better). (2) Rollout throughput N=1 vs N=4 on the same prompts —
    trajectories/s per fleet size, outputs pinned bit-identical across
    fleet sizes. (3) Chaos: ``sampler=1:rollout_step=1:lost`` kills a
    member mid-run over 3 rollouts; ``steps_lost_to_sampler_death``
    must be 0 (lose a sampler, not the run — orphaned groups are
    reassigned and regenerate bit-identically from the journal).
    Deterministic, CPU-sized, in-process."""
    import time
    import jax
    import numpy as np
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.sampling import derive_rollout_seeds
    from dla_tpu.rollout import (SamplerFleet, SamplerFleetConfig,
                                 ensure_cpu_sync_dispatch)
    from dla_tpu.serving import ServingConfig

    # must precede the first jax computation below — the CPU client
    # bakes the dispatch mode in at creation (see actor_fleet)
    ensure_cpu_sync_dispatch()
    cfg = ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=192,
        num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_length=128, remat="none", dtype="float32",
        param_dtype="float32")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    gen = GenerationConfig(max_new_tokens=6, do_sample=True,
                           temperature=1.0, eos_token_id=-1,
                           pad_token_id=0)
    rows = 8
    rs = np.random.RandomState(7)
    lens = rs.randint(4, 11, (rows,))
    width = int(lens.max())
    ids = np.zeros((rows, width), np.int32)
    mask = np.zeros_like(ids)
    for i, n in enumerate(lens):
        ids[i, :n] = rs.randint(3, 500, (n,))
        mask[i, :n] = 1
    seeds = derive_rollout_seeds(11, rows)
    scfg = ServingConfig(page_size=4, num_pages=96, num_slots=4,
                         max_model_len=48, max_prefill_batch=2,
                         fault_plan="")
    delay_s, branch = 0.05, 2

    # --- (1) refit fanout serial vs broadcast at N=4, (2) N=4 rollout
    fleet4 = SamplerFleet(
        model, params, gen, scfg,
        SamplerFleetConfig(samplers=4, fanout_branch=branch,
                           refit_delay_s=delay_s))
    t0 = time.perf_counter()
    fleet4.publish_params_serial(params, version=1)
    serial_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    fleet4.publish_params(params, version=2)
    bcast_s = time.perf_counter() - t0
    fanout_speedup = serial_s / bcast_s
    fleet4.generate(ids, mask, seeds)          # warm-up: compiles
    t0 = time.perf_counter()
    out4 = fleet4.generate(ids, mask, seeds)
    n4_s = time.perf_counter() - t0
    fleet4.close()

    fleet1 = SamplerFleet(model, params, gen, scfg,
                          SamplerFleetConfig(samplers=1))
    fleet1.generate(ids, mask, seeds)          # warm-up
    t0 = time.perf_counter()
    out1 = fleet1.generate(ids, mask, seeds)
    n1_s = time.perf_counter() - t0
    fleet1.close()
    identical = bool(np.array_equal(np.asarray(out1["response_tokens"]),
                                    np.asarray(out4["response_tokens"])))

    # --- (3) lose a sampler mid-run: zero learner steps lost
    chaos = SamplerFleet(
        model, params, gen,
        ServingConfig(page_size=4, num_pages=96, num_slots=4,
                      max_model_len=48, max_prefill_batch=2,
                      fault_plan="sampler=1:rollout_step=1:lost"),
        SamplerFleetConfig(samplers=2, lease_ttl_s=0.3))
    steps_lost = 0
    for _ in range(3):
        try:
            ck = chaos.generate(ids, mask, seeds)
            if np.asarray(ck["response_tokens"]).shape[0] != rows:
                steps_lost += 1
        except Exception:  # noqa: BLE001 — a lost run IS the metric
            steps_lost += 1
    snap = chaos.fleet_metrics.snapshot()
    chaos.close()

    return {
        "metric": "rollout_fleet_fanout_speedup",
        "value": round(fanout_speedup, 2),
        "unit": "x",
        "detail": {
            "fanout_speedup": round(fanout_speedup, 2),
            "serial_refit_ms": round(serial_s * 1e3, 1),
            "broadcast_refit_ms": round(bcast_s * 1e3, 1),
            "refit_delay_ms": delay_s * 1e3,
            "samplers": 4,
            "fanout_branch": branch,
            "fanout_waves": 2,
            "trajectories_per_s_n1": round(rows / n1_s, 2),
            "trajectories_per_s_n4": round(rows / n4_s, 2),
            "fleet_scaling": round(n1_s / n4_s, 2),
            "outputs_identical_n1_n4": identical,
            "steps_lost_to_sampler_death": steps_lost,
            "retired_samplers": int(
                snap["rollout/fleet/retired_samplers"]),
            "reassigned_rollouts": int(
                snap["rollout/fleet/reassigned_rollouts"]),
            "params_m": round(count_params(params) / 1e6)},
    }


def run_serving_spec_bench() -> dict:
    """Speculative-serving A/B on the long-tail response-length mix:
    the SAME prompts and per-row budgets through two serving engines —
    blockwise draft/verify speculation ON (int8 self-draft) vs OFF.
    The headline is the decode-throughput speedup (tokens/s spec-on /
    spec-off, higher is better); detail carries the measured draft
    acceptance rate, per-arm tokens/s and slot-steps per token (a
    speculative round retires up to K+1 tokens per slot-step, so the
    spec arm's slot-steps/token drops with acceptance). Deterministic,
    CPU-sized, in-process."""
    import time
    import jax
    import numpy as np
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.serving import ServingConfig, ServingEngine

    # deliberately latency-bound: per-step FLOPs are tiny so the fixed
    # per-dispatch cost dominates the decode step, the CPU stand-in for
    # the TPU's memory-bandwidth-bound decode — the regime where a
    # verify over K+1 columns costs about the same as one column and
    # speculation pays
    cfg = ModelConfig(
        vocab_size=256, hidden_size=32, intermediate_size=96,
        num_layers=2, num_heads=2, num_kv_heads=2,
        max_seq_length=128, remat="none", dtype="float32",
        param_dtype="float32")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    max_new = [9, 9, 9, 12, 12, 18, 24, 72]
    rows, longest = len(max_new), max(max_new)
    gen = GenerationConfig(max_new_tokens=longest, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    rs = np.random.RandomState(7)
    lens = rs.randint(4, 11, (rows,))
    prompts = [list(rs.randint(3, 250, (n,)).astype(int)) for n in lens]
    num_slots = 4
    # k=8: a speculative round is dominated by its two fixed dispatch
    # costs (draft scan + verify), so a deeper block amortizes them
    # over more committed tokens — the CPU analogue of the TPU's
    # memory-bound decode step
    spec = {"enabled": True, "k": 8, "draft": "int8"}
    reps = 5

    def run_arm(spec_on: bool):
        eng = ServingEngine(model, params, gen, ServingConfig(
            page_size=4, num_pages=128, num_slots=num_slots,
            max_model_len=96, max_prefill_batch=2,
            speculative=spec if spec_on else None))
        # compile warmup off the clock: every prefill bucket the mix
        # hits at BOTH prefill batch shapes (3 requests = one batch of 2
        # + one of 1 — the eager sampling ops compile per batch shape),
        # plus one decode round per slot population — the 2-token budget
        # is what forces the draft+verify pair (or plain decode) to
        # trace, and the first arm must not eat compiles the second arm
        # gets from the process-wide op cache
        slot_w = eng.cache.geom.slot_window
        for width in sorted({eng.scheduler.bucket_width(len(p))
                             for p in prompts}):
            plen = min(width, slot_w - 2)
            for _ in range(3):
                eng.submit([3 + (i % 251) for i in range(plen)], 2)
        eng.run_until_drained()
        # the measured window is small (~100 ms on CPU), so wall-clock
        # noise swamps a single pass: repeat the identical mix and take
        # the fastest pass — scheduling is deterministic, so every rep
        # does the same work and the min is the least-perturbed timing
        dts = []
        for _ in range(reps):
            steps0 = eng.engine_steps
            t0 = time.perf_counter()
            for p, m in zip(prompts, max_new):
                eng.submit(p, m)
            eng.run_until_drained(max_steps=5000)
            dts.append(time.perf_counter() - t0)
            steps = eng.engine_steps - steps0
        snap = eng.metrics.snapshot()
        eng.close()
        return min(dts), steps, snap

    dt_on, steps_on, snap_on = run_arm(True)
    dt_off, steps_off, snap_off = run_arm(False)
    tokens = sum(max_new)
    tps_on = tokens / dt_on
    tps_off = tokens / dt_off
    prop = snap_on["serving/spec/proposed_tokens"]
    acceptance = snap_on["serving/spec/accepted_tokens"] / max(prop, 1)
    return {
        "metric": "serving_spec_decode_speedup",
        "value": round(tps_on / tps_off, 4),
        "unit": "x",
        "detail": {
            "decode_tokens_per_s_spec_on": round(tps_on, 1),
            "decode_tokens_per_s_spec_off": round(tps_off, 1),
            "acceptance_rate": round(acceptance, 4),
            "slot_steps_per_token_spec_on":
                round(steps_on * num_slots / tokens, 4),
            "slot_steps_per_token_spec_off":
                round(steps_off * num_slots / tokens, 4),
            "spec_rounds": snap_on["serving/spec/rounds"] / reps,
            "spec_rollbacks": snap_on["serving/spec/rollbacks"] / reps,
            "reps": reps,
            "k": spec["k"],
            "draft": spec["draft"],
            "tokens": tokens,
            "rows": rows,
            "num_slots": num_slots,
            "longest_row": longest,
            "params_m": round(count_params(params) / 1e6)},
    }


def run_serving_tenant_bench() -> dict:
    """Multi-tenant LoRA serving A/B (dla_tpu/serving/tenancy): N=4
    tenants' adapters batched into ONE engine (per-slot adapter gather,
    one decode compile across the whole tenant mix) vs serving the same
    tenants' interleaved arrival trace on a single-tenant engine that
    pays a merge-and-republish weight swap at every tenant switch. The
    headline is the batched arm's tokens/s speedup over the serial-swap
    arm (higher is better) — the model is sized so a swap costs real
    merge + republish time, not just a pointer flip, since that is the
    cost the adapter pool removes;
    detail pins per-tenant greedy outputs identical across arms,
    decode_step_compiles == 1 on the batched engine, and the
    noisy-tenant quota probe (a flooding tenant's sheds must land on
    itself only, every other tenant's requests finishing untouched).
    Deterministic, CPU-sized, in-process."""
    import jax
    from dla_tpu.eval.eval_latency import measure_multi_tenant
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer

    cfg = ModelConfig(
        vocab_size=2048, hidden_size=384, intermediate_size=768,
        num_layers=4, num_heads=6, num_kv_heads=6,
        max_seq_length=128, remat="none", dtype="float32",
        param_dtype="float32", lora_r=8, lora_alpha=16.0)
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    srv = {"new_tokens": 8, "arrival_rate": 1000.0, "seed": 7,
           "page_size": 4, "num_pages": 96, "num_slots": 4,
           "max_model_len": 48, "max_prefill_batch": 2,
           "chunked_prefill": {"chunk": 8},
           "tenancy": {"tenants": 4, "requests_per_tenant": 3}}
    row = measure_multi_tenant(model, params, srv)
    return {
        "metric": "serving_tenant_batched_speedup",
        "value": round(row["batched_speedup"], 3),
        "unit": "x",
        "detail": {
            "tokens_per_s_batched": round(row["tokens_per_s_batched"], 1),
            "tokens_per_s_serial": round(row["tokens_per_s_serial"], 1),
            "outputs_identical": bool(row["outputs_identical"]),
            "decode_step_compiles": int(row["decode_step_compiles"]),
            "adapter_publishes": int(row["adapter_publishes"]),
            "adapter_resident": int(row["adapter_resident"]),
            "noisy_isolated": bool(row["noisy_isolated"]),
            "noisy_shed": int(row["noisy_shed"]),
            "others_shed": int(row["others_shed"]),
            "others_finished": int(row["others_finished"]),
            "tenants": row["tenants"],
            "requests_per_tenant": row["requests_per_tenant"],
            "lora_rank": row["lora_rank"],
            "params_m": round(count_params(params) / 1e6)},
    }


def run_serving_fleet_bench() -> dict:
    """Fleet-routing A/B/C on a shared-prefix request mix: the SAME
    prompts through (1) a single engine, (2) an N=4 fleet with random
    placement, and (3) an N=4 fleet with cache-aware routing (peek +
    load + sticky-prefix affinity). The headline is the routed fleet's
    decode-throughput speedup over random placement (higher is better —
    random scatters each prompt family across members and destroys
    cross-request prefix reuse); detail carries per-arm decode tokens/s
    (N=1 vs N=4 scaling), per-arm prefix-cache hit rates and the
    routed fleet's hit-rate retention vs the single engine, the greedy
    bit-identity check across all three arms, and a scale-down drain
    exercise (queued work rebalanced to peers, zero lost requests).
    Deterministic placement and outputs, CPU-sized, in-process."""
    import time
    import jax
    import numpy as np
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.serving import (
        TERMINAL_STATES,
        FleetConfig,
        FleetRouter,
        ServingConfig,
        ServingEngine,
        ServingMetrics,
    )

    cfg = ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=192,
        num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_length=128, remat="none", dtype="float32",
        param_dtype="float32")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    new_tokens, chunk = 8, 8
    gen = GenerationConfig(max_new_tokens=new_tokens, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    families, per_family = 4, 8
    rs = np.random.RandomState(7)
    prompts = []
    for _ in range(families):
        head = [int(t) for t in rs.randint(3, 500, (16,))]
        for _ in range(per_family):
            prompts.append(head + [int(t)
                                   for t in rs.randint(3, 500, (4,))])
    tokens = len(prompts) * new_tokens
    prompt_tokens = sum(len(p) for p in prompts)
    engines, reps = 4, 3

    def build_engine(slot=0):
        # two slots per engine: the single-engine arm is deliberately
        # slot-bound, so fleet scaling measures real added capacity;
        # fault_plan="" pins members fault-free under $DLA_FAULT_PLAN
        return ServingEngine(model, params, gen, ServingConfig(
            page_size=4, num_pages=96, num_slots=2, max_model_len=48,
            max_prefill_batch=2, prefill_chunk=chunk, prefix_cache=True,
            fault_plan=""))

    def warm(eng):
        # compile warmup (chunk fn + decode) off the clock; random
        # tokens can't collide with a family prefix
        eng.submit([int(t) for t in rs.randint(3, 500, (chunk + 1,))], 1)
        eng.run_until_drained()
        eng.metrics = ServingMetrics()

    def drive(eng):
        # burst-submit the whole mix and take the fastest of `reps`
        # identical passes — scheduling and placement are
        # deterministic, so the min is the least-perturbed timing
        dts, outs = [], None
        for _ in range(reps):
            t0 = time.perf_counter()
            rids = [eng.submit(p, new_tokens) for p in prompts]
            results = eng.run_until_drained(max_steps=20000)
            dts.append(time.perf_counter() - t0)
            outs = [list(results[r].generated) for r in rids]
        return min(dts), outs

    def run_single():
        eng = build_engine()
        warm(eng)
        dt, outs = drive(eng)
        hit = eng.metrics.snapshot()["serving/prefix_cache/hit_tokens"]
        eng.close()
        return dt, outs, hit / (reps * prompt_tokens)

    def run_fleet(placement):
        router = FleetRouter(
            lambda slot: build_engine(slot),
            FleetConfig(engines=engines, min_engines=1,
                        max_engines=engines, placement=placement))
        for m in router.members():
            warm(m.engine)
        dt, outs = drive(router)
        hit = sum(s["serving/prefix_cache/hit_tokens"]
                  for s in router.engine_snapshots())
        return router, dt, outs, hit / (reps * prompt_tokens)

    dt_single, outs_single, hit_single = run_single()
    r_rand, dt_rand, outs_rand, hit_rand = run_fleet("random")
    r_rand.close()
    r_routed, dt_routed, outs_routed, hit_routed = run_fleet("cache_aware")

    # scale-down drain on the routed fleet: queued work must move to
    # peers and every request must still reach a terminal state
    rids = [r_routed.submit(p, new_tokens) for p in prompts]
    r_routed.scale_down()
    results = r_routed.run_until_drained(max_steps=20000)
    lost = sum(1 for r in rids
               if results[r].state not in TERMINAL_STATES)
    fleet_snap = r_routed.fleet_snapshot()
    r_routed.close()

    tps_routed = tokens / dt_routed
    tps_rand = tokens / dt_rand
    tps_single = tokens / dt_single
    return {
        "metric": "serving_fleet_routed_speedup",
        "value": round(tps_routed / tps_rand, 4),
        "unit": "x",
        "detail": {
            "decode_tokens_per_s_routed": round(tps_routed, 1),
            "decode_tokens_per_s_random": round(tps_rand, 1),
            "decode_tokens_per_s_single": round(tps_single, 1),
            "fleet_n4_tokens_per_s_scaling":
                round(tps_routed / tps_single, 4),
            "hit_rate_routed": round(hit_routed, 4),
            "hit_rate_random": round(hit_rand, 4),
            "hit_rate_single": round(hit_single, 4),
            "hit_rate_retention":
                round(hit_routed / max(hit_single, 1e-9), 4),
            "outputs_identical":
                bool(outs_single == outs_rand == outs_routed),
            "requests_lost_scale_down": lost,
            "rebalanced_requests":
                int(fleet_snap["serving/fleet/rebalanced_requests"]),
            "routed_by_prefix":
                int(fleet_snap["serving/fleet/routed_by_prefix"]),
            "routed_by_load":
                int(fleet_snap["serving/fleet/routed_by_load"]),
            "engines": engines,
            "reps": reps,
            "requests": len(prompts),
            "families": families,
            "params_m": round(count_params(params) / 1e6)},
    }


def run_serving_disagg_bench() -> dict:
    """Prefill/decode disaggregation A/B on a long-prompt burst: the
    SAME prompts through (1) a mixed co-scheduled fleet of 3 members and
    (2) a role-split fleet of 1 prefill + 2 decode members where every
    finished prefix ships to a decode member as a KV migration ticket
    (one jitted gather + one jitted scatter per handoff). The headline
    is the disaggregated fleet's ITL p99 speedup over the mixed fleet
    (higher is better — decode members never interleave prefill chunks,
    so the inter-token tail loses its head-of-line stalls); detail
    carries per-arm ITL p50/p99 and decode tokens/s, a single-engine
    reference arm, the migrated-page throughput, and the greedy
    bit-identity check across all arms (migration resumes from the
    exact committed KV columns). Deterministic, CPU-sized,
    in-process."""
    import time
    import jax
    import numpy as np
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.serving import (
        FleetConfig,
        FleetRouter,
        ServingConfig,
        ServingEngine,
        ServingMetrics,
    )
    from dla_tpu.utils.logging import percentile

    cfg = ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=192,
        num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_length=128, remat="none", dtype="float32",
        param_dtype="float32")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    # long prompts + small chunk: the regime where co-scheduled prefill
    # chunks head-of-line-block decode steps and inflate the ITL tail
    new_tokens, chunk, prompt_len = 8, 8, 24
    gen = GenerationConfig(max_new_tokens=new_tokens, do_sample=False,
                           eos_token_id=-1, pad_token_id=0)
    rs = np.random.RandomState(7)
    prompts = [[int(t) for t in rs.randint(3, 500, (prompt_len,))]
               for _ in range(24)]
    tokens = len(prompts) * new_tokens
    n_prefill, n_decode, reps = 1, 2, 3
    engines = n_prefill + n_decode
    roles = ("prefill",) * n_prefill + ("decode",) * n_decode

    def build_engine(role="mixed"):
        # fault_plan="" pins members fault-free under $DLA_FAULT_PLAN
        return ServingEngine(model, params, gen, ServingConfig(
            page_size=4, num_pages=96, num_slots=2, max_model_len=48,
            max_prefill_batch=2, prefill_chunk=chunk, prefix_cache=True,
            fault_plan="", role=role))

    def warm(eng):
        # compile warmup off the clock; decode-role members gate
        # submit(), so warm those through restore() — the handoff-only
        # admission surface compiles the same chunk + decode fns
        prompt = [int(t) for t in rs.randint(3, 500, (chunk + 1,))]
        if eng.cfg.role == "decode":
            eng.restore(prompt, 1, generated=[], arrival_time=0.0)
        else:
            eng.submit(prompt, 1)
        eng.run_until_drained()

    def drive(eng, member_engines):
        # burst-submit the whole mix; per rep, reset the member metrics
        # and keep the least-perturbed (fastest) rep's ITL samples
        best = None
        for _ in range(reps):
            for e in member_engines:
                e.metrics = ServingMetrics()
            t0 = time.perf_counter()
            rids = [eng.submit(p, new_tokens) for p in prompts]
            results = eng.run_until_drained(max_steps=20000)
            dt = time.perf_counter() - t0
            outs = [list(results[r].generated) for r in rids]
            itl = [s for e in member_engines
                   for s in e.metrics.itl_ms.samples]
            pages = sum(
                e.metrics.snapshot()["serving/migration/migrated_pages"]
                for e in member_engines)
            if best is None or dt < best[0]:
                best = (dt, outs, itl, pages)
        return best

    def run_single():
        eng = build_engine()
        warm(eng)
        dt, outs, itl, _ = drive(eng, [eng])
        eng.close()
        return dt, outs, itl

    def run_fleet(role_split):
        router = FleetRouter(
            lambda slot: build_engine(
                roles[slot] if role_split else "mixed"),
            FleetConfig(engines=engines, min_engines=1,
                        max_engines=engines,
                        roles=roles if role_split else None))
        for m in router.members():
            warm(m.engine)
        dt, outs, itl, pages = drive(
            router, [m.engine for m in router.members()])
        router.close()
        return dt, outs, itl, pages

    dt_single, outs_single, itl_single = run_single()
    dt_mixed, outs_mixed, itl_mixed, _ = run_fleet(False)
    dt_disagg, outs_disagg, itl_disagg, pages = run_fleet(True)

    p99_mixed = percentile(itl_mixed, 99.0)
    p99_disagg = percentile(itl_disagg, 99.0)
    return {
        "metric": "serving_disagg_itl_p99_speedup",
        "value": round(p99_mixed / max(p99_disagg, 1e-9), 4),
        "unit": "x",
        "detail": {
            "itl_p99_ms_disagg": round(p99_disagg, 3),
            "itl_p99_ms_mixed": round(p99_mixed, 3),
            "itl_p99_ms_single": round(percentile(itl_single, 99.0), 3),
            "itl_p50_ms_disagg": round(percentile(itl_disagg, 50.0), 3),
            "itl_p50_ms_mixed": round(percentile(itl_mixed, 50.0), 3),
            "decode_tokens_per_s_disagg": round(tokens / dt_disagg, 1),
            "decode_tokens_per_s_mixed": round(tokens / dt_mixed, 1),
            "decode_tokens_per_s_single": round(tokens / dt_single, 1),
            "migrated_pages_per_s": round(pages / dt_disagg, 1),
            "migrated_pages": int(pages),
            "outputs_identical":
                bool(outs_single == outs_mixed == outs_disagg),
            "prefill_engines": n_prefill,
            "decode_engines": n_decode,
            "prompt_len": prompt_len,
            "reps": reps,
            "requests": len(prompts),
            "params_m": round(count_params(params) / 1e6)},
    }


def run_serving_resilience_bench() -> dict:
    """Serving-resilience chaos bench: a supervised engine
    (dla_tpu/serving/resilience) driven through the full serving fault
    plan — a wedged step, a device error, NaN logits, and a request
    burst — with admission control on. The headline is requests lost
    (MUST be 0: every submitted request reaches a terminal state, work
    is replayed across engine rebuilds, overload is shed explicitly);
    detail carries the shed rate, p99 TTFT under the burst, restart
    count and breaker state. Deterministic, CPU-sized, in-process."""
    import jax
    import numpy as np
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.serving import (
        RequestState,
        ServingConfig,
        ServingEngine,
        Supervisor,
        SupervisorConfig,
        TERMINAL_STATES,
    )
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.utils.logging import percentile

    cfg = ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=192,
        num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_length=128, remat="none", dtype="float32",
        param_dtype="float32")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    gen = GenerationConfig(max_new_tokens=10, do_sample=False,
                           eos_token_id=-1)
    plan = ("engine_step=2:wedge:0.3;engine_step=4:device_error;"
            "engine_step=6:nan_logits;engine_step=8:burst=8")
    engines = []

    def factory():
        eng = ServingEngine(model, params, gen, ServingConfig(
            page_size=4, num_pages=64, num_slots=2, max_model_len=32,
            max_prefill_batch=2, fault_plan=plan,
            shed={"max_queue_depth": 6}))
        engines.append(eng)
        return eng

    sup = Supervisor(factory, SupervisorConfig(
        watchdog_timeout_s=0.05, watchdog_poll_s=0.01, max_restarts=3))
    rs = np.random.RandomState(0)
    # uniform prompt length: one prefill bucket, so the only compile-
    # exempt watchdog window is each engine's first step
    prompts = [list(rs.randint(3, 500, (6,)).astype(int))
               for _ in range(8)]
    for p in prompts:
        sup.submit(p, 10)
    results = sup.run()
    sup.close()
    reqs = list(results.values())
    lost = sum(1 for r in reqs if r.state not in TERMINAL_STATES)
    shed = sum(1 for r in reqs if r.state is RequestState.SHED)
    ttfts = [(r.first_token_time - r.arrival_time) * 1000.0
             for r in reqs if r.first_token_time is not None]
    return {
        "metric": "serving_requests_lost",
        "value": lost,
        "unit": "requests",
        "detail": {
            "requests_lost": lost,
            "requests_total": len(reqs),
            "shed_rate": round(shed / max(len(reqs), 1), 4),
            "ttft_ms_p99": round(percentile(ttfts, 99.0), 2)
            if ttfts else None,
            "restarts": sup.restarts,
            "failures": sup.failures,
            "breaker_tripped": bool(sup.tripped),
            "replayed_requests": sup.replayed,
            "decode_compiles_per_engine": [
                e.decode_compiles for e in engines],
            "params_m": round(count_params(params) / 1e6)},
    }


def run_serving_gateway_bench() -> dict:
    """Gateway wire-overhead + federation chaos bench (serving.gateway
    / serving.federation). Two passes on the same greedy trace:

      1. retention — the trace in-process vs over localhost HTTP
         through one streaming gateway (SSE per-token events); the
         headline is wire tokens/s as a fraction of in-process
         (>= 0.9 expected: serialization + loopback must not dominate
         a CPU-sized decode)
      2. chaos — the trace through a TWO-gateway federation with a
         ``net=`` fault plan (delay, drop, disconnect mid-stream);
         requests_lost MUST be 0 (dropped / disconnected streams are
         replayed bit-identically from the router journal) and outputs
         stay identical to in-process

    Deterministic, CPU-sized, in-process (sockets on loopback only)."""
    import http.client
    import tempfile
    import threading
    import time

    import jax
    import numpy as np
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.resilience.faults import FaultPlan
    from dla_tpu.serving import (
        FederatedRouter,
        FederationConfig,
        GossipBeater,
        ServingConfig,
        ServingEngine,
        ServingGateway,
    )

    cfg = ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=192,
        num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_length=128, remat="none", dtype="float32",
        param_dtype="float32")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    new_tokens = 8
    gen = GenerationConfig(max_new_tokens=new_tokens, do_sample=False,
                           eos_token_id=-1)
    kw = dict(page_size=4, num_pages=64, num_slots=2, max_model_len=32,
              max_prefill_batch=2, prefill_chunk=4, prefix_cache=True,
              fault_plan="")

    def make_engine():
        return ServingEngine(model, params, gen, ServingConfig(**kw))

    rs = np.random.RandomState(0)
    prompts = [[int(t) for t in rs.randint(3, 500, (6,))]
               for _ in range(8)]

    def http_generate(port, prompt):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=300)
        try:
            conn.request("POST", "/v1/generate", json.dumps(
                {"prompt": prompt, "max_new_tokens": new_tokens}
            ).encode(), {"Content-Type": "application/json"})
            resp = conn.getresponse()
            toks = []
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line.startswith(b"data: "):
                    continue
                ev = json.loads(line[len(b"data: "):])
                if ev.get("done"):
                    break
                toks.append(int(ev["token"]))
            return toks
        finally:
            conn.close()

    # compile-warm prompts: same length/count as the measured trace
    # (covers the full prefill batch + both-slots decode shapes) but
    # disjoint tokens, so the prefix cache stays cold for the clock
    warm_prompts = [[1 + (i + j) % 2 for i in range(6)]
                    for j in range(len(prompts))]

    def drive_wire(port, batch):
        """The trace over the wire with one concurrent client per
        request — the engine batches exactly as the in-process arm."""
        out = [None] * len(batch)

        def client(i):
            out[i] = http_generate(port, batch[i])
        ts = [threading.Thread(target=client, args=(i,),
                               name=f"dla-bench-gwclient-{i}",
                               daemon=True)
              for i in range(len(batch))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        return out

    # pass 1: retention ------------------------------------------------
    eng = make_engine()
    for p in warm_prompts:             # compile warm, off the clock
        eng.submit(p, new_tokens)
    eng.run_until_drained()
    t0 = time.perf_counter()
    rids = [eng.submit(p, new_tokens) for p in prompts]
    results = eng.run_until_drained(max_steps=5000)
    dt_in = time.perf_counter() - t0
    ref = [list(results[r].generated) for r in rids]
    tokens = sum(len(o) for o in ref)

    gw = ServingGateway(make_engine())
    drive_wire(gw.port, warm_prompts)      # wire + compile warm
    t0 = time.perf_counter()
    wire = [list(o) for o in drive_wire(gw.port, prompts)]
    dt_wire = time.perf_counter() - t0
    gw.close()
    retention = (tokens / dt_wire) / (tokens / dt_in)

    # pass 2: federation chaos ----------------------------------------
    gdir = tempfile.mkdtemp(prefix="dla-gw-bench-")
    gws = [ServingGateway(make_engine()) for _ in range(2)]
    beats = [GossipBeater(g, gdir, n) for g, n in zip(gws, "ab")]
    plan = FaultPlan.parse(
        "net=3:delay:0.01;net=5:drop;net=8:disconnect")
    fed = FederatedRouter(gdir, FederationConfig(),
                          fault_plan=plan)
    deadline = time.monotonic() + 10
    while len(fed.live_peers()) < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    fids = [fed.submit(p, new_tokens) for p in prompts]
    out = fed.results(timeout_s=300)
    chaos = [out[f].tokens for f in fids]
    lost = fed.requests_lost
    for b in beats:
        b.stop()
    for g in gws:
        g.close()

    return {
        "metric": "serving_gateway_wire_retention",
        "value": round(retention, 4),
        "unit": "x",
        "detail": {
            "tokens_per_s_in_process": round(tokens / dt_in, 1),
            "tokens_per_s_wire": round(tokens / dt_wire, 1),
            "wire_overhead_ms_per_token": round(
                1e3 * (dt_wire - dt_in) / max(tokens, 1), 3),
            "requests_lost": lost,
            "requests_total": len(prompts),
            "replayed_requests": fed.replayed,
            "faults_injected": 3,
            "outputs_identical_wire": bool(wire == ref),
            "outputs_identical_chaos": bool(chaos == ref),
            "new_tokens": new_tokens,
            "params_m": round(count_params(params) / 1e6)},
    }


def run_observability_bench() -> dict:
    """Distributed-tracing overhead target (telemetry.trace_context /
    tools/trace_merge.py): the same greedy wire trace through a
    streaming gateway twice — process tracing OFF (the disabled
    default: the zero-work-when-disabled pin) vs ON (enabled tracer +
    per-process span spool) — reporting the wire throughput fraction
    tracing costs. The detail pins the contract: measured-section
    engine compile counts identical across arms (tracing adds zero
    compiles), outputs bit-identical, zero ring drops and spool write
    errors in the traced arm, and the traced arm's spool must merge
    into a strictly valid Chrome trace via tools/trace_merge.py.

    Deterministic, CPU-sized, in-process (sockets on loopback only)."""
    import http.client
    import shutil
    import tempfile
    import threading
    import time
    from pathlib import Path

    import jax
    import numpy as np
    from dla_tpu.generation.engine import GenerationConfig
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.serving import ServingConfig, ServingEngine, \
        ServingGateway
    from dla_tpu.telemetry.trace import Tracer, get_tracer, \
        install_tracer

    cfg = ModelConfig(
        vocab_size=512, hidden_size=64, intermediate_size=192,
        num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_length=128, remat="none", dtype="float32",
        param_dtype="float32")
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    new_tokens = 8
    gen = GenerationConfig(max_new_tokens=new_tokens, do_sample=False,
                           eos_token_id=-1)
    kw = dict(page_size=4, num_pages=64, num_slots=2, max_model_len=32,
              max_prefill_batch=2, prefill_chunk=4, prefix_cache=True,
              fault_plan="")
    rs = np.random.RandomState(0)
    prompts = [[int(t) for t in rs.randint(3, 500, (6,))]
               for _ in range(8)]
    warm_prompts = [[1 + (i + j) % 2 for i in range(6)]
                    for j in range(len(prompts))]

    def http_generate(port, prompt):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=300)
        try:
            conn.request("POST", "/v1/generate", json.dumps(
                {"prompt": prompt, "max_new_tokens": new_tokens}
            ).encode(), {"Content-Type": "application/json"})
            resp = conn.getresponse()
            toks = []
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line.startswith(b"data: "):
                    continue
                ev = json.loads(line[len(b"data: "):])
                if ev.get("done"):
                    break
                toks.append(int(ev["token"]))
            return toks
        finally:
            conn.close()

    def drive_wire(port, batch):
        out = [None] * len(batch)

        def client(i):
            out[i] = http_generate(port, batch[i])
        ts = [threading.Thread(target=client, args=(i,),
                               name=f"dla-bench-obsclient-{i}",
                               daemon=True)
              for i in range(len(batch))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        return out

    # Interleaved best-of-N A/B against ONE gateway instance. The
    # 2-slot CPU wire drive is bimodal (an engine-loop idle-poll park
    # just as submits land serializes the tiny batch) and the mode is
    # sticky per process phase — separate per-arm gateways measure
    # scheduler luck, not tracing. Toggling the process tracer between
    # measured drives on the same gateway hits both arms with the same
    # artifact; max over reps is the steady-state throughput per arm.
    reps = 5
    spool = tempfile.mkdtemp(prefix="dla-obs-spool-")
    prev = get_tracer()
    traced = Tracer.from_config(
        {"enabled": True, "capacity": 1 << 17,
         "spool_dir": spool, "proc": "gateway"})
    eng = ServingEngine(model, params, gen, ServingConfig(**kw))
    gw = ServingGateway(eng)
    try:
        drive_wire(gw.port, warm_prompts)   # compile + wire warm
        install_tracer(traced)
        drive_wire(gw.port, warm_prompts)   # traced-path + spool warm
        install_tracer(prev)
        c0 = (eng.decode_compiles, eng.prefill_compiles)
        best = {False: 0.0, True: 0.0}
        outs = {False: None, True: None}
        for _ in range(reps):
            for arm in (False, True):
                install_tracer(traced if arm else prev)
                t0 = time.perf_counter()
                rep = [list(o)
                       for o in drive_wire(gw.port, prompts)]
                dt = time.perf_counter() - t0
                tps = sum(len(o) for o in rep) / dt
                if outs[arm] is None or tps > best[arm]:
                    best[arm], outs[arm] = tps, rep
        # summed over ALL measured drives of BOTH arms — tracing must
        # add zero compiles, so the pinned total is (0, 0)
        compiles = (eng.decode_compiles - c0[0],
                    eng.prefill_compiles - c0[1])
    finally:
        install_tracer(prev)
        gw.close()
    stats = {"spooled": traced.spooled, "dropped": traced.dropped,
             "spool_errors": traced.spool_errors}
    traced.detach_spool()
    off_tps, on_tps = best[False], best[True]
    off_out, on_out = outs[False], outs[True]
    off_compiles = on_compiles = compiles

    from tools.trace_merge import merge_dir, validate
    merged = merge_dir(Path(spool))
    problems = validate(merged)
    n_spans = sum(1 for e in merged["traceEvents"]
                  if e.get("ph") == "X")
    shutil.rmtree(spool, ignore_errors=True)

    return {
        "metric": "observability_wire_overhead_frac",
        "value": round(1.0 - on_tps / max(off_tps, 1e-9), 4),
        "unit": "fraction",
        "detail": {
            "tokens_per_s_traced_off": round(off_tps, 1),
            "tokens_per_s_traced_on": round(on_tps, 1),
            # must be equal across arms: tracing adds zero compiles to
            # the measured section (both expected (0, 0) post-warm)
            "compiles_measured_off": list(off_compiles),
            "compiles_measured_on": list(on_compiles),
            "outputs_identical": bool(on_out == off_out),
            "trace_spooled_records": int(stats.get("spooled", 0)),
            "trace_dropped": int(stats.get("dropped", 0)),
            "trace_spool_errors": int(stats.get("spool_errors", 0)),
            "merged_trace_valid": not problems,
            "merged_trace_spans": int(n_spans),
            "new_tokens": new_tokens,
            "params_m": round(count_params(params) / 1e6)},
    }


def run_resilience_bench() -> dict:
    """Recovery-overhead microbench for the fault-tolerance stack
    (dla_tpu/resilience): one tiny SFT run with an injected checkpoint
    io_error AND an injected NaN step, async checkpointing on. Reports
    what resilience costs when faults actually happen:

      - checkpoint stall ms — how long save() blocked the step loop
        (async: host-snapshot only), vs the same save through the
        synchronous Checkpointer
      - steps lost — extra step executions the NaN guard spent
        (retries); with a one-shot transient fault the retry succeeds,
        so the run still reaches max_steps with zero skipped data
      - io retries — backoff retries the background writer needed

    Deterministic, CPU-sized, in-process (no tunnel involved)."""
    import shutil as _shutil
    import tempfile

    import jax
    from dla_tpu.checkpoint.checkpointer import Checkpointer
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.fused_ce import model_fused_ce
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.training.trainer import Trainer

    cfg = ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=192,
        num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_length=64, remat="none", dtype="float32",
        param_dtype="float32")
    micro, seq, max_steps, save_every = 2, 64, 8, 2
    mesh = build_mesh(MeshConfig(data=1, fsdp=-1, model=1, sequence=1))
    model = Transformer(cfg)

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        loss, _ = model_fused_ce(model, p, batch)
        return loss, {}

    rs = np.random.RandomState(0)

    def batches():
        local_bs = micro * mesh.devices.size
        while True:
            yield {
                "input_ids": rs.randint(1, cfg.vocab_size, (local_bs, seq)
                                        ).astype(np.int32),
                "attention_mask": np.ones((local_bs, seq), np.int32),
                "labels": rs.randint(1, cfg.vocab_size, (local_bs, seq)
                                     ).astype(np.int32),
            }

    out_dir = tempfile.mkdtemp(prefix="dla_bench_resil_")
    try:
        config = {
            "experiment_name": "bench_resilience",
            "optimization": {
                "total_batch_size": micro * mesh.devices.size,
                "micro_batch_size": micro, "learning_rate": 1e-4,
                "max_train_steps": max_steps, "lr_scheduler": "constant",
                "max_grad_norm": 1.0,
            },
            "logging": {"output_dir": out_dir, "log_dir": None,
                        "save_every_steps": save_every,
                        "log_every_steps": 10 ** 6},
            "hardware": {"gradient_accumulation_steps": 1},
            "resilience": {
                "async_checkpointing": True,
                "save_retries": 3, "retry_backoff_s": 0.05,
                # io_error hits the background writer of the step-2 save;
                # nan hits the forward of step 5 (one-shot -> the guard's
                # retry of the same batch recovers bit-exactly)
                "fault_plan": "step=2:io_error;step=5:nan",
            },
        }
        with jax.sharding.set_mesh(mesh):
            trainer = Trainer(config=config, mesh=mesh, loss_fn=loss_fn,
                              params=model.init(jax.random.key(0)),
                              param_specs=model.partition_specs())
            trainer.fit(batches(), rng=jax.random.key(1))
            trainer.checkpoint_wait()
            ck = trainer.checkpointer
            async_stall = (ck.total_stall_ms
                           / max(1, ck.saves_started))
            retries = ck.retries_total
            bad_steps = trainer.guard.bad_steps_total
            final_step = trainer.step

            # the comparison bar: the same state through the blocking
            # Checkpointer — what every save used to cost the step loop
            sync = Checkpointer(out_dir + "/sync", keep_last_n=1)
            t0 = time.perf_counter()
            sync.save(final_step, trainer._state_tree(), {"step": final_step})
            sync_stall = (time.perf_counter() - t0) * 1000.0
    finally:
        _shutil.rmtree(out_dir, ignore_errors=True)

    return {
        "metric": "resilience_checkpoint_stall_ms",
        "value": round(async_stall, 3),
        "unit": "ms",
        "vs_baseline": round(async_stall / max(sync_stall, 1e-9), 4),
        "detail": {
            # steps lost = retried executions; the run still reaches
            # max_steps (transient NaN retried on the same batch)
            "steps_lost_to_faults": int(bad_steps),
            "final_step": int(final_step),
            "target_steps": int(max_steps),
            "io_retries": int(retries),
            "async_stall_ms_per_save": round(async_stall, 3),
            "sync_save_ms": round(sync_stall, 3),
            "saves_completed": int(ck.saves_completed),
            "fault_plan": "step=2:io_error;step=5:nan",
        },
    }


def run_elastic_resilience_bench() -> dict:
    """Host-loss recovery bench for the elastic gang
    (dla_tpu/resilience/elastic): a simulated 8-host pod loses host 1
    mid-run (fault plan ``host=1:step=6:lost``), the gang detects the
    stale lease within ``lease_ttl_steps``, exits resumably, and the
    run resumes on a 4-device mesh from the latest checkpoint with the
    global batch preserved (grad accum recomputed). Reports:

      - steps replayed — detection step minus the resumed-from step
        (work re-done because the outage landed between saves)
      - detection lag — steps from the injected loss to the agreed
        shrink (bounded by lease_ttl_steps)
      - elastic badput — the detect -> restart -> resume gap as the
        resumed run's ``telemetry/badput_elastic`` fraction

    Deterministic, CPU-sized, in-process (no tunnel involved)."""
    import shutil as _shutil
    import tempfile

    import jax
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.fused_ce import model_fused_ce
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.resilience import ElasticRestart
    from dla_tpu.training.trainer import Trainer

    cfg = ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=192,
        num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_length=64, remat="none", dtype="float32",
        param_dtype="float32")
    seq, max_steps, save_every = 64, 12, 4
    lease_ttl_steps, fault_step, lost_host = 3, 5, 1
    devices = jax.devices()
    if len(devices) < 8:
        return {"metric": "elastic_steps_replayed",
                "error": f"needs 8 CPU devices, have {len(devices)}"}
    mesh8 = build_mesh(MeshConfig(data=1, fsdp=8, model=1, sequence=1),
                       devices=devices[:8])
    mesh4 = build_mesh(MeshConfig(data=1, fsdp=4, model=1, sequence=1),
                       devices=devices[:4])
    model = Transformer(cfg)

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        loss, _ = model_fused_ce(model, p, batch)
        return loss, {}

    def batches():
        rs = np.random.RandomState(0)
        while True:
            yield {
                "input_ids": rs.randint(1, cfg.vocab_size, (8, seq)
                                        ).astype(np.int32),
                "attention_mask": np.ones((8, seq), np.int32),
                "labels": rs.randint(1, cfg.vocab_size, (8, seq)
                                     ).astype(np.int32),
            }

    def make_config(out_dir, world, fault_plan=""):
        return {
            "experiment_name": "bench_elastic",
            "optimization": {
                "total_batch_size": 8, "micro_batch_size": 1,
                "learning_rate": 1e-4, "max_train_steps": max_steps,
                "lr_scheduler": "constant", "max_grad_norm": 1.0,
            },
            "data": {"prefetch": 0},
            "logging": {"output_dir": out_dir, "log_dir": None,
                        "save_every_steps": save_every,
                        "log_every_steps": 10 ** 6},
            "hardware": {"gradient_accumulation_steps": 1},
            "resilience": {
                "fault_plan": fault_plan,
                "elastic": {"enabled": True, "lease_ttl_s": 0,
                            "lease_ttl_steps": lease_ttl_steps,
                            "sim_world": world},
            },
        }

    out_dir = tempfile.mkdtemp(prefix="dla_bench_elastic_")
    try:
        fault_plan = f"host={lost_host}:step={fault_step}:lost"
        with jax.sharding.set_mesh(mesh8):
            trainer = Trainer(
                config=make_config(out_dir, 8, fault_plan), mesh=mesh8,
                loss_fn=loss_fn, params=model.init(jax.random.key(0)),
                param_specs=model.partition_specs())
            detect_step = None
            try:
                trainer.fit(batches(), rng=jax.random.key(1))
            except ElasticRestart as exc:
                detect_step = exc.step
        if detect_step is None:
            return {"metric": "elastic_steps_replayed",
                    "error": "host loss was never detected"}
        with jax.sharding.set_mesh(mesh4):
            resumed = Trainer(
                config=make_config(out_dir, 4), mesh=mesh4,
                loss_fn=loss_fn, params=model.init(jax.random.key(0)),
                param_specs=model.partition_specs())
            resumed.fit(batches(), rng=jax.random.key(1), resume=True)
            resume_step = None
            for ev in resumed.recorder.events:
                if ev["kind"] == "elastic_resume":
                    resume_step = ev["step"]
            badput = resumed.clock.badput()["elastic"]
            final_step = resumed.step
    finally:
        _shutil.rmtree(out_dir, ignore_errors=True)

    replayed = detect_step - (resume_step or 0)
    return {
        "metric": "elastic_steps_replayed",
        "value": int(replayed),
        "unit": "steps",
        # a full save interval is the worst case for an outage landing
        # right before a save; <1.0 means detection beat the cadence
        "vs_baseline": round(replayed / save_every, 4),
        "detail": {
            "detect_step": int(detect_step),
            "resumed_from_step": int(resume_step or 0),
            "detection_lag_steps": int(detect_step - fault_step),
            "lease_ttl_steps": int(lease_ttl_steps),
            "badput_elastic": round(float(badput), 6),
            "final_step": int(final_step),
            "target_steps": int(max_steps),
            "train_step_compiles": int(resumed.train_step_compiles),
            "fault_plan": fault_plan,
        },
    }


def run_telemetry_bench() -> dict:
    """Telemetry-overhead microbench (dla_tpu/telemetry): the same tiny
    SFT run twice — telemetry on (step clock + in-graph collector +
    flight recorder + registry mirror) vs ``logging.telemetry.enabled:
    false`` — reporting ms/step overhead and the ratio. The collector
    rides the one jitted step (train_step_compiles stays 1, asserted),
    so the expected overhead is host-side accounting only: a few
    perf_counter calls per step.

    Deterministic, CPU-sized, in-process (no tunnel involved)."""
    import shutil as _shutil
    import tempfile

    import jax
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.fused_ce import model_fused_ce
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.training.trainer import Trainer

    cfg = ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=192,
        num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_length=64, remat="none", dtype="float32",
        param_dtype="float32")
    micro, seq, max_steps = 2, 64, 24
    mesh = build_mesh(MeshConfig(data=1, fsdp=-1, model=1, sequence=1))
    model = Transformer(cfg)

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        loss, _ = model_fused_ce(model, p, batch)
        return loss, {}

    def batches(seed):
        rs = np.random.RandomState(seed)
        local_bs = micro * mesh.devices.size
        while True:
            yield {
                "input_ids": rs.randint(1, cfg.vocab_size, (local_bs, seq)
                                        ).astype(np.int32),
                "attention_mask": np.ones((local_bs, seq), np.int32),
                "labels": rs.randint(1, cfg.vocab_size, (local_bs, seq)
                                     ).astype(np.int32),
            }

    def one_run(enabled: bool) -> tuple:
        out_dir = tempfile.mkdtemp(prefix="dla_bench_tel_")
        try:
            config = {
                "experiment_name": "bench_telemetry",
                "optimization": {
                    "total_batch_size": micro * mesh.devices.size,
                    "micro_batch_size": micro, "learning_rate": 1e-4,
                    "max_train_steps": max_steps,
                    "lr_scheduler": "constant", "max_grad_norm": 1.0,
                },
                "logging": {"output_dir": out_dir, "log_dir": None,
                            "save_every_steps": 0,
                            "log_every_steps": 8,
                            "telemetry": {"enabled": enabled}},
                "hardware": {"gradient_accumulation_steps": 1},
                "resilience": {"watchdog": {"enabled": False}},
            }
            with jax.sharding.set_mesh(mesh):
                trainer = Trainer(config=config, mesh=mesh,
                                  loss_fn=loss_fn,
                                  params=model.init(jax.random.key(0)),
                                  param_specs=model.partition_specs())
                t0 = time.perf_counter()
                trainer.fit(batches(0), rng=jax.random.key(1))
                wall = time.perf_counter() - t0
                return (wall * 1000.0 / max_steps,
                        trainer.train_step_compiles,
                        trainer.clock.goodput())
        finally:
            _shutil.rmtree(out_dir, ignore_errors=True)

    base_ms, base_compiles, _ = one_run(enabled=False)
    tel_ms, tel_compiles, goodput = one_run(enabled=True)
    overhead_ms = tel_ms - base_ms

    return {
        "metric": "telemetry_overhead_ms_per_step",
        "value": round(overhead_ms, 3),
        "unit": "ms",
        # ratio of instrumented to bare step time: ~1.0 = free telemetry
        "vs_baseline": round(tel_ms / max(base_ms, 1e-9), 4),
        "detail": {
            "base_ms_per_step": round(base_ms, 3),
            "telemetry_ms_per_step": round(tel_ms, 3),
            "goodput": round(goodput, 4),
            # both must be 1: the collector adds ZERO extra compiles
            "train_step_compiles_base": int(base_compiles),
            "train_step_compiles_telemetry": int(tel_compiles),
            "steps": int(max_steps),
        },
    }


def run_introspect_bench() -> dict:
    """XLA-introspection overhead target (dla_tpu/telemetry/
    xla_introspect): the same tiny SFT run twice with telemetry on —
    ``xla_introspect.enabled: true`` (AOT-dispatching wrapper,
    per-call argument fingerprinting, cost/memory gauges) vs ``false``
    (plain jit dispatch) — reporting ms/step overhead. Also asserts the
    wrapper's zero-extra-compile contract (train_step_compiles == 1
    both ways) and surfaces the compiled-fn analytics the wrapper read.

    Deterministic, CPU-sized, in-process (no tunnel involved)."""
    import shutil as _shutil
    import tempfile

    import jax
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.fused_ce import model_fused_ce
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.training.trainer import Trainer

    cfg = ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=192,
        num_layers=2, num_heads=4, num_kv_heads=4,
        max_seq_length=64, remat="none", dtype="float32",
        param_dtype="float32")
    micro, seq, max_steps = 2, 64, 24
    mesh = build_mesh(MeshConfig(data=1, fsdp=-1, model=1, sequence=1))
    model = Transformer(cfg)

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        loss, _ = model_fused_ce(model, p, batch)
        return loss, {}

    def batches(seed):
        rs = np.random.RandomState(seed)
        local_bs = micro * mesh.devices.size
        while True:
            yield {
                "input_ids": rs.randint(1, cfg.vocab_size, (local_bs, seq)
                                        ).astype(np.int32),
                "attention_mask": np.ones((local_bs, seq), np.int32),
                "labels": rs.randint(1, cfg.vocab_size, (local_bs, seq)
                                     ).astype(np.int32),
            }

    def one_run(introspect: bool) -> tuple:
        out_dir = tempfile.mkdtemp(prefix="dla_bench_xi_")
        try:
            config = {
                "experiment_name": "bench_introspect",
                "optimization": {
                    "total_batch_size": micro * mesh.devices.size,
                    "micro_batch_size": micro, "learning_rate": 1e-4,
                    "max_train_steps": max_steps,
                    "lr_scheduler": "constant", "max_grad_norm": 1.0,
                },
                "logging": {"output_dir": out_dir, "log_dir": None,
                            "save_every_steps": 0,
                            "log_every_steps": 8,
                            "telemetry": {"enabled": True,
                                          "xla_introspect": {
                                              "enabled": introspect}}},
                "hardware": {"gradient_accumulation_steps": 1},
                "resilience": {"watchdog": {"enabled": False}},
            }
            with jax.sharding.set_mesh(mesh):
                trainer = Trainer(config=config, mesh=mesh,
                                  loss_fn=loss_fn,
                                  params=model.init(jax.random.key(0)),
                                  param_specs=model.partition_specs())
                t0 = time.perf_counter()
                trainer.fit(batches(0), rng=jax.random.key(1))
                wall = time.perf_counter() - t0
                stats = dict(getattr(trainer._jit_train_step, "stats",
                                     None) or {})
                return (wall * 1000.0 / max_steps,
                        trainer.train_step_compiles, stats)
        finally:
            _shutil.rmtree(out_dir, ignore_errors=True)

    base_ms, base_compiles, _ = one_run(introspect=False)
    xi_ms, xi_compiles, stats = one_run(introspect=True)
    overhead_ms = xi_ms - base_ms

    return {
        "metric": "introspect_overhead_ms_per_step",
        "value": round(overhead_ms, 3),
        "unit": "ms",
        # ratio of introspected to plain-jit step time: ~1.0 = free
        "vs_baseline": round(xi_ms / max(base_ms, 1e-9), 4),
        "detail": {
            "base_ms_per_step": round(base_ms, 3),
            "introspect_ms_per_step": round(xi_ms, 3),
            # both must be 1: the AOT wrapper adds ZERO extra compiles
            "train_step_compiles_base": int(base_compiles),
            "train_step_compiles_introspect": int(xi_compiles),
            "xla_flops": stats.get("flops"),
            "xla_bytes_accessed": stats.get("bytes_accessed"),
            "roofline_compute_bound": stats.get("roofline_compute_bound"),
            "steps": int(max_steps),
        },
    }


def _child_env(mode: str) -> dict:
    from _cpuhost import prepend_pythonpath, scrubbed_cpu_env
    if mode == "cpu":
        env = scrubbed_cpu_env(repo_root=_REPO_ROOT)
    else:
        env = prepend_pythonpath(dict(os.environ), _REPO_ROOT)
    env["DLA_BENCH_PLATFORM"] = mode
    return env


def _extract_json_line(text: str) -> dict | None:
    for line in (text or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if parsed.get("metric"):
                return parsed
    return None


def _relay_child(mode: str, timeout_s: float) -> tuple:
    """Run the bench in a bounded subprocess; (JSON line | None, status)
    where status is "ok" | "timeout" | "failed" — the caller retries a
    smaller config only on "failed" (an OOM-class crash); a timeout means
    the tunnel is wedged and further accel attempts would just burn the
    driver's budget."""
    stdout, stderr, rc = "", "", None
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], cwd=_REPO_ROOT,
            env=_child_env(mode), capture_output=True, text=True,
            timeout=timeout_s)
        stdout, stderr, rc = proc.stdout, proc.stderr, proc.returncode
    except subprocess.TimeoutExpired as e:
        stdout = (e.stdout or b"") if isinstance(e.stdout, str) else \
            (e.stdout or b"").decode("utf-8", "replace")
        stderr = (e.stderr or b"") if isinstance(e.stderr, str) else \
            (e.stderr or b"").decode("utf-8", "replace")
        print(f"[bench] {mode} child timed out after {timeout_s:.0f}s",
              file=sys.stderr)
        sys.stderr.write(stderr or "")
        return _extract_json_line(stdout), "timeout"
    except Exception as e:
        print(f"[bench] {mode} child failed to launch: {e}", file=sys.stderr)
        return None, "failed"
    sys.stderr.write(stderr or "")
    result = _extract_json_line(stdout)
    if result is not None and result.get("error"):
        # a child line carrying an error is a failure, not a measurement
        print(f"[bench] {mode} child line carries error: "
              f"{result['error'][:200]}", file=sys.stderr)
        return None, "failed"
    if result is not None:
        return result, "ok"
    print(f"[bench] {mode} child emitted no JSON line (rc={rc})",
          file=sys.stderr)
    # rc=1 is the accel child's "no backend ever came up" exit
    # (_try_devices returned None) — retrying a smaller config cannot
    # help; rc!=1 crashes are OOM-class and worth a smaller retry
    return None, ("no_backend" if rc == 1 else "failed")


def _emit_and_maybe_extra() -> None:
    """Child-side: print the headline SFT line; with DLA_BENCH_EXTRA set,
    also measure PPO rollout+update and decode, appending everything to
    BENCH_extra.json (the BASELINE.md evidence artifact)."""
    headline = run_bench()
    print(json.dumps(headline))
    if not os.environ.get("DLA_BENCH_EXTRA"):
        return
    extra = [headline]
    for fn in (run_ppo_bench, run_decode_bench, run_serving_bench,
               run_serving_prefix_bench, run_serving_spec_bench,
               run_serving_fleet_bench, run_serving_disagg_bench,
               run_serving_gateway_bench, run_serving_tenant_bench,
               run_elastic_resilience_bench,
               run_rollout_fleet_bench, run_observability_bench):
        try:
            res = fn()
        except Exception as e:  # noqa: BLE001 — extras must not kill the line
            res = {"metric": fn.__name__, "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(res), file=sys.stderr)
        extra.append(res)
    # BENCH_extra.json is the on-chip evidence artifact BASELINE.md
    # cites — a forced-CPU fallback run must not clobber it. Each
    # artifact carries its provenance (commit + wall time) so the
    # BASELINE.md tables can cite rows unambiguously.
    import datetime
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=_REPO_ROOT,
            capture_output=True, text=True, timeout=10)
        commit = proc.stdout.strip() if proc.returncode == 0 else ""
    except Exception:  # noqa: BLE001 — provenance must not kill the line
        commit = ""
    commit = commit or "unknown"
    extra.append({"provenance": {
        "commit": commit,
        "utc": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds")}})
    import jax
    name = ("BENCH_extra.json" if jax.devices()[0].platform != "cpu"
            else "BENCH_extra_cpu.json")
    with open(os.path.join(_REPO_ROOT, name), "w") as fh:
        json.dump(extra, fh, indent=1)


def main() -> int:
    if "resilience" in sys.argv[1:]:
        # fault-tolerance recovery-overhead target: deterministic and
        # CPU-sized, so it runs in-process on the forced-CPU platform
        # (no tunnel, no child ladder)
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_resilience_bench()))
        return 0
    if "elastic-resilience" in sys.argv[1:]:
        # host-loss chaos target: simulated 8-host gang loses a host and
        # resumes at 4 devices; needs the 8-device virtual CPU mesh
        from _cpuhost import force_cpu_platform
        force_cpu_platform(8)
        print(json.dumps(run_elastic_resilience_bench()))
        return 0
    if "rollout" in sys.argv[1:]:
        # disaggregated-rollout A/B target: same in-process forced-CPU
        # pattern; headline is padding waste recovered (higher better)
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_rollout_bench()))
        return 0
    if "rollout-fleet" in sys.argv[1:]:
        # elastic sampler-fleet target: serial-vs-broadcast refit
        # fanout at N=4 (headline, higher better), trajectories/s N=1
        # vs N=4, and steps-lost-to-sampler-death chaos (must be 0)
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_rollout_fleet_bench()))
        return 0
    if "serving-spec" in sys.argv[1:]:
        # speculative-serving A/B target: same in-process forced-CPU
        # pattern; headline is decode tokens/s speedup (higher better)
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_serving_spec_bench()))
        return 0
    if "serving-fleet" in sys.argv[1:]:
        # fleet-routing A/B/C target: same in-process forced-CPU
        # pattern; headline is routed-vs-random decode speedup
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_serving_fleet_bench()))
        return 0
    if "serving-disagg" in sys.argv[1:]:
        # prefill/decode disaggregation A/B target: same in-process
        # forced-CPU pattern; headline is ITL p99 speedup (higher
        # better)
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_serving_disagg_bench()))
        return 0
    if "serving-tenant" in sys.argv[1:]:
        # multi-tenant LoRA serving A/B target: same in-process
        # forced-CPU pattern; headline is batched-vs-serial-swap
        # tokens/s speedup, detail pins output identity, one decode
        # compile across the tenant mix, and noisy-tenant isolation
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_serving_tenant_bench()))
        return 0
    if "serving-gateway" in sys.argv[1:]:
        # gateway wire-overhead + federation chaos target: same
        # in-process forced-CPU pattern (loopback sockets only);
        # headline is wire tokens/s retention (higher better), detail
        # pins requests_lost to 0 under net= disconnect chaos
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_serving_gateway_bench()))
        return 0
    if "observability" in sys.argv[1:]:
        # distributed-tracing overhead target: wire + spool cost with
        # tracing on vs off, compile counts pinned identical across
        # arms and the spool merged via tools/trace_merge.py
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_observability_bench()))
        return 0
    if "serving-resilience" in sys.argv[1:]:
        # supervised-serving chaos target: same in-process forced-CPU
        # pattern; headline is requests lost (must be 0)
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_serving_resilience_bench()))
        return 0
    if "telemetry" in sys.argv[1:]:
        # telemetry-overhead target: same in-process forced-CPU pattern
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_telemetry_bench()))
        return 0
    if "introspect" in sys.argv[1:]:
        # XLA-introspection overhead target: same in-process forced-CPU
        # pattern; headline is ms/step added by the AOT wrapper
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        print(json.dumps(run_introspect_bench()))
        return 0
    mode = os.environ.get("DLA_BENCH_PLATFORM")
    if mode == "cpu":
        # CPU child: force the platform before backend init, run, emit.
        from _cpuhost import force_cpu_platform
        force_cpu_platform()
        _emit_and_maybe_extra()
        return 0
    if mode == "probe":
        # Probe child: devices-up + tiny jit only; parent bounds us with
        # the short probe timeout. rc=1 = no backend (same as accel).
        # Keep the default retry policy: the tunnel's documented
        # transient first-contact UNAVAILABLE must not demote a healthy
        # TPU run to the CPU fallback (retries fit the probe budget).
        if _try_devices() is None:
            return 1
        print(json.dumps(run_probe()))
        return 0
    if mode == "accel":
        # Accelerator child: may hang in tunnel init — parent bounds us.
        if _try_devices() is None:
            return 1
        _emit_and_maybe_extra()
        return 0

    # Parent orchestrator: NEVER initializes jax (backend init can hang);
    # every jax touch happens in a time-bounded child. The accelerator
    # attempt descends through micro batch sizes in FRESH children — an
    # HBM OOM can poison a live TPU client (observed: later ops fail with
    # RESOURCE_EXHAUSTED), so each retry gets a clean process.
    if "--extra" in sys.argv:
        os.environ["DLA_BENCH_EXTRA"] = "1"
    probe_t = float(os.environ.get("DLA_BENCH_PROBE_TIMEOUT", "180"))
    accel_t = float(os.environ.get("DLA_BENCH_ACCEL_TIMEOUT", "900"))
    cpu_t = float(os.environ.get("DLA_BENCH_CPU_TIMEOUT", "600"))
    preset = os.environ.get("DLA_BENCH_MICRO")
    try:  # a malformed value must not break the always-emit contract
        ladder = (int(preset),) if preset else (8, 6, 4)
    except ValueError:
        print(f"[bench] ignoring malformed DLA_BENCH_MICRO={preset!r}",
              file=sys.stderr)
        ladder = (8, 6, 4)
    # Rung 1: fail-fast tunnel-health probe. Only a healthy probe opens
    # the expensive measure ladder; a hung/failed probe sends us straight
    # to the CPU fallback at ~probe_t cost instead of n*accel_t.
    probe, probe_status = _relay_child("probe", probe_t)
    result = None
    # A probe that emitted its line but then wedged (timeout during
    # teardown) still demonstrated a wedge-class tunnel — gate on status,
    # not just on having parsed a line.
    if probe is None or probe_status != "ok":
        print(f"[bench] tunnel probe unhealthy ({probe_status}); "
              f"skipping accelerator ladder", file=sys.stderr)
    elif probe.get("detail", {}).get("platform") == "cpu":
        print("[bench] probe came up on CPU only; skipping accelerator "
              "ladder", file=sys.stderr)
    else:
        print(f"[bench] tunnel probe healthy: {probe.get('detail')}",
              file=sys.stderr)
        for micro in ladder:
            os.environ["DLA_BENCH_MICRO"] = str(micro)
            result, status = _relay_child("accel", accel_t)
            if result is not None or status in ("timeout", "no_backend"):
                break
            print(f"[bench] accel attempt at micro={micro} produced no "
                  f"result; retrying smaller", file=sys.stderr)
    if result is None:
        result, _ = _relay_child("cpu", cpu_t)
    if result is None:  # last resort: the line must still be emitted
        result = {
            "metric": "sft_tokens_per_sec_per_chip", "value": 0.0,
            "unit": "tok/s/chip", "vs_baseline": 0.0,
            "error": "no jax backend available (accelerator and forced-CPU "
                     "fallback both failed)",
        }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except Exception as e:  # absolute backstop: never exit without the line
        if os.environ.get("DLA_BENCH_PLATFORM"):
            # Child process: an exception here is an OOM-class failure the
            # PARENT must see as rc!=0 so its ladder retries a smaller
            # config. Emitting the 0.0 line from the child instead would
            # hand the parent a "valid" result and freeze the ladder on
            # the first rung (observed: micro=8 HBM OOM reported as 0.0).
            print(f"[bench] child crashed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            sys.exit(2)
        print(json.dumps({
            "metric": "sft_tokens_per_sec_per_chip", "value": 0.0,
            "unit": "tok/s/chip", "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(0)
