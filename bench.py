"""Headline benchmark: SFT training throughput, tokens/sec/chip.

Prints ONE JSON line:
  {"metric": "sft_tokens_per_sec_per_chip", "value": N, "unit": "tok/s/chip",
   "vs_baseline": R}

``vs_baseline`` normalizes against the north-star target (BASELINE.md:
>= 0.8x the per-device throughput of the 8xH100 NCCL reference stack).
Neither repo publishes absolute H100 numbers (SURVEY.md sec 6), so the
comparison is made in hardware-normalized terms: a well-tuned
DeepSpeed-ZeRO3 run sustains ~40% MFU on H100-class hardware, so the
baseline per-chip token rate on *this* chip class is
0.8 * 0.40 * peak_flops / (6 * n_params) and

  vs_baseline = measured_MFU / (0.8 * 0.40)

i.e. vs_baseline >= 1.0 means this framework beats 0.8x the H100 baseline
after normalizing for per-chip peak FLOPs.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

PEAK_BF16_FLOPS = {
    # per-chip peak bf16 FLOP/s by device kind (substring match)
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v4": 275e12, "v6": 918e12, "trillium": 918e12,
    "cpu": 5e11,
}
BASELINE_MFU = 0.8 * 0.40  # 0.8x of a 40%-MFU H100-class DeepSpeed baseline


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_BF16_FLOPS.items():
        if key in kind:
            return val
    return 197e12 if device.platform != "cpu" else PEAK_BF16_FLOPS["cpu"]


def count_params(params) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(params)))


def main() -> None:
    on_accel = jax.devices()[0].platform != "cpu"
    from dla_tpu.models.config import ModelConfig
    from dla_tpu.models.transformer import Transformer
    from dla_tpu.ops.losses import cross_entropy_loss
    from dla_tpu.parallel.mesh import MeshConfig, build_mesh
    from dla_tpu.training.trainer import Trainer

    if on_accel:
        # ~460M-param Llama-style model: big enough to exercise the MXU,
        # small enough that params + fp32 Adam state fit one v5e chip.
        cfg = ModelConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_layers=24, num_heads=16, num_kv_heads=16,
            max_seq_length=2048, remat="full")
        micro, seq, steps, warmup = 4, 2048, 6, 2
    else:  # CPU fallback so the bench always emits its line
        cfg = ModelConfig(
            vocab_size=512, hidden_size=128, intermediate_size=384,
            num_layers=4, num_heads=8, num_kv_heads=8,
            max_seq_length=256, remat="none", dtype="float32",
            param_dtype="float32")
        micro, seq, steps, warmup = 2, 256, 4, 1

    mesh = build_mesh(MeshConfig(data=1, fsdp=-1, model=1, sequence=1))
    model = Transformer(cfg)
    params = model.init(jax.random.key(0))
    n_params = count_params(params)

    def loss_fn(p, frozen, batch, rng):
        del frozen, rng
        logits = model.apply(p, batch["input_ids"],
                             attention_mask=batch["attention_mask"])
        loss, _ = cross_entropy_loss(logits, batch["labels"])
        return loss, {}

    config = {
        "experiment_name": "bench",
        "optimization": {
            "total_batch_size": micro * mesh.devices.size,
            "micro_batch_size": micro, "learning_rate": 1e-4,
            "max_train_steps": steps, "lr_scheduler": "constant",
            "max_grad_norm": 1.0,
        },
        "logging": {"output_dir": "/tmp/dla_bench_ckpt", "log_dir": None},
        "hardware": {"gradient_accumulation_steps": 1},
    }
    with jax.sharding.set_mesh(mesh):
        trainer = Trainer(config=config, mesh=mesh, loss_fn=loss_fn,
                          params=params, param_specs=model.partition_specs())
        rs = np.random.RandomState(0)
        local_bs = micro * mesh.devices.size
        batch = {
            "input_ids": rs.randint(1, cfg.vocab_size, (local_bs, seq)
                                    ).astype(np.int32),
            "attention_mask": np.ones((local_bs, seq), np.int32),
            "labels": rs.randint(1, cfg.vocab_size, (local_bs, seq)
                                 ).astype(np.int32),
        }
        for i in range(warmup):
            trainer.step_on_batch(batch, jax.random.key(i))
        t0 = time.perf_counter()
        for i in range(steps):
            trainer.step_on_batch(batch, jax.random.key(100 + i))
        dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    tokens = local_bs * seq * steps
    tok_s_chip = tokens / dt / n_chips
    mfu = tok_s_chip * 6 * n_params / peak_flops(jax.devices()[0])
    vs_baseline = mfu / BASELINE_MFU
    print(json.dumps({
        "metric": "sft_tokens_per_sec_per_chip",
        "value": round(tok_s_chip, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
