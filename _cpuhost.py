"""Force the virtual-CPU host platform in an axon-tunnel environment.

Single home for the environment dance used by tests/conftest.py,
__graft_entry__.py, and bench.py. The ambient environment registers a
TPU-tunnel PJRT plugin ("axon") via a sitecustomize hook whenever
``PALLAS_AXON_POOL_IPS`` is set, with ``JAX_PLATFORMS=axon`` exported —
and the hook overrides platform selection through ``jax.config``, so env
vars alone do not stick. Backend init through the tunnel can HANG (not
just raise), so anything that wants the virtual CPU mesh must force it
*before* first device use, or scrub the plugin out of a child process's
environment entirely.

Stdlib-only at module level (jax is imported lazily inside functions),
so this is importable before jax in conftest-style preambles.
"""
from __future__ import annotations

import os
import re
from typing import Optional


def prepend_pythonpath(env: dict, root: str) -> dict:
    env["PYTHONPATH"] = (
        root + os.pathsep + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
    return env


def set_cpu_env(n_devices: Optional[int] = None,
                env: Optional[dict] = None) -> dict:
    """Set JAX_PLATFORMS=cpu (+ host device count) on ``env`` (default:
    os.environ). An existing device-count flag with a DIFFERENT value is
    replaced, not kept — otherwise a caller needing 8 devices inherits an
    ambient count of 4 forever. Returns the mapping for chaining."""
    env = os.environ if env is None else env
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = env.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want, flags)
        else:
            flags = (flags + " " + want).strip()
        env["XLA_FLAGS"] = flags
    return env


def force_cpu_platform(n_devices: Optional[int] = None) -> bool:
    """conftest-style in-process forcing: env + jax.config, before any
    backend initializes. Returns True when the live backend is CPU with
    at least ``n_devices`` devices (or just CPU when n_devices is None);
    False means a backend with the wrong platform/count already exists
    and the caller should re-exec in a scrubbed child process."""
    set_cpu_env(n_devices)
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:
        devices = jax.devices()
    except Exception:
        return False
    if devices[0].platform != "cpu":
        return False
    return n_devices is None or len(devices) >= n_devices


def scrubbed_cpu_env(n_devices: Optional[int] = None,
                     repo_root: Optional[str] = None) -> dict:
    """Child-process env with the axon plugin disarmed and CPU forced.
    Without PALLAS_AXON_POOL_IPS the sitecustomize hook is a no-op, so
    the child never registers the tunnel plugin at all — it cannot hang
    in plugin init before user code runs."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    set_cpu_env(n_devices, env)
    if repo_root:
        prepend_pythonpath(env, repo_root)
    return env
